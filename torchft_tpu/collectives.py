"""Reconfigurable collective communication for cross-replica-group traffic.

Plays the role of the reference's reconfigurable ProcessGroup abstraction
(reference torchft/process_group.py:109-166): a ``Collectives`` object can be
``configure()``d onto a new membership every time the quorum changes, using a
per-quorum store prefix so stale members never cross-talk (reference
torchft/manager.py:470-477).

TPU-first design: these collectives deliberately run on the HOST, outside
XLA. Intra-replica-group parallelism (the HSDP "shard" dimension) belongs to
pjit/``shard_map`` over the slice's ICI mesh and never spans a failure
domain; only the cross-group gradient average travels through this layer
(over DCN in production). Because the transport is plain sockets, a dead
replica group surfaces as an abortable socket error instead of a wedged
device collective — the property the reference buys with subprocess-isolated
NCCL ("Baby" process groups, reference torchft/process_group.py:551-1064).

Ops are asynchronous: each returns a :class:`Work` whose result is the
reduced pytree. A single-thread executor issues ops in submission order (the
ordering contract collective backends require), and the GIL is released for
the duration of each native call.
"""

from __future__ import annotations

import ctypes
import json
import os
import threading
import time
from abc import ABC, abstractmethod
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, replace
from datetime import timedelta
from enum import IntEnum
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import _native
from ._native import _check, _lib, _ms


class ReduceOp(IntEnum):
    """Matches tft::ReduceOp in native/src/collectives.h. AVG is SUM followed
    by a host-side divide (the reference divides in the manager too,
    torchft/manager.py:279-291)."""

    SUM = 0
    PRODUCT = 1
    MIN = 2
    MAX = 3
    AVG = 100


# Native dtype codes (tft::Dtype). Other dtypes (e.g. f16) are accumulated
# in f32 and cast back. bfloat16 ships natively — 2 bytes on the wire, half
# the DCN traffic of an f32 upcast; reduction math is f32 per ring hop with
# round-to-nearest-even back to bf16 (for long-chain exact accumulation,
# cast leaves to f32 before the allreduce).
import ml_dtypes

_BF16 = np.dtype(ml_dtypes.bfloat16)
_NATIVE_DTYPES = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.int64): 3,
    _BF16: 4,
}


class Work:
    """Handle for an async collective; the result is the output pytree.

    Mirrors the role of torch.distributed Work / torch futures in the
    reference (torchft/process_group.py:318-330).
    """

    def __init__(self, future: "Future[Any]") -> None:
        self._future = future

    def wait(self, timeout: Optional[timedelta] = None) -> Any:
        return self._future.result(
            timeout=timeout.total_seconds() if timeout is not None else None
        )

    def result(self, timeout: Optional[timedelta] = None) -> Any:
        return self.wait(timeout)

    def done(self) -> bool:
        return self._future.done()

    def exception(self) -> Optional[BaseException]:
        return self._future.exception()

    def add_done_callback(self, fn: Callable[["Future[Any]"], None]) -> None:
        self._future.add_done_callback(fn)

    def then(self, fn: Callable[[Any], Any]) -> "Work":
        """Returns a Work whose result is fn(result); errors propagate."""
        out: "Future[Any]" = Future()

        def _chain(f: "Future[Any]") -> None:
            exc = f.exception()
            if exc is not None:
                out.set_exception(exc)
                return
            try:
                out.set_result(fn(f.result()))
            except Exception as e:  # noqa: BLE001 - propagate into future
                out.set_exception(e)

        self._future.add_done_callback(_chain)
        return Work(out)


def _completed(value: Any) -> Work:
    f: "Future[Any]" = Future()
    f.set_result(value)
    return Work(f)


def _divide_leaf(leaf: Any, divisor: float) -> Any:
    """Same-dtype divide for the divisor/AVG contract: integers
    floor-divide (matching the multi-member ring), floats keep their
    dtype. Handles numpy and jax leaves alike."""
    dtype = np.dtype(getattr(leaf, "dtype", np.float64))
    if np.issubdtype(dtype, np.integer):
        return leaf // int(divisor)
    return (leaf / divisor).astype(dtype)


def _flatten(tree: Any) -> Tuple[List[Any], Any]:
    """Flatten a pytree without importing jax at module load."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return leaves, treedef


def _unflatten(treedef: Any, leaves: Sequence[Any]) -> Any:
    import jax

    return jax.tree_util.tree_unflatten(treedef, leaves)


@dataclass
class TreeShard:
    """This rank's shard of a flat-packed pytree, the unit the sharded
    (split) collectives trade in.

    ``reduce_scatter`` returns one; ``allgather_into`` consumes one. The
    pytree is packed into one contiguous flat buffer per accumulation-dtype
    group (the same grouping the fused allreduce uses, or a single f32
    group on the q8 wire), and the shard is the union of the per-stripe
    ring chunks this rank owns, compacted in stripe order. ``values`` is
    what a caller updates in place of the full tree (the weight-update
    sharding of PAPERS.md #1: outer-optimizer state and FLOPs scale with
    the shard, not the model); everything else is layout bookkeeping that
    must ride along unchanged so ``allgather_into`` can scatter the
    updated shard back to the identical wire schedule on every member.
    """

    # group name -> this rank's flat shard (jax or numpy array)
    values: Dict[str, Any]
    # group name -> total flat elements of the group's full buffer
    counts: Dict[str, int]
    # group name -> [(start, len)] element ranges this rank owns, in
    # compaction order (global positions within the group's flat buffer)
    ranges: Dict[str, List[Tuple[int, int]]]
    # group name -> the stripe partition pinned for this sync; an
    # allgather_into of a DIFFERENT wire dtype must reuse it or the two
    # ops would partition the payload differently (see native
    # collectives.h shard-layout contract)
    layout: Dict[str, int]
    # group name -> numpy dtype of the group's packed buffer
    dtypes: Dict[str, Any]
    # group name -> leaf indices packed into that group (sig order)
    groups: Dict[str, List[int]]
    treedef: Any
    sig: Any
    rank: int
    world_size: int
    # packer used for the device-side pack/unpack (None on the host path)
    packer: Any = None
    # host path only: which leaves were jax arrays on input
    was_jax: Any = None
    # sharded comm plan that produced this shard (plan_reduce_scatter
    # only): plan_allgather_into routes the updated shard back through
    # the same precompiled schedule — layout agreement by construction.
    plan: Any = None

    def replace_values(self, values: Dict[str, Any]) -> "TreeShard":
        """Same shard layout, new per-group values (e.g. the updated
        parameter shard after an outer-optimizer step)."""
        return replace(self, values=values)


class Collectives(ABC):
    """Reconfigurable collectives over replica groups.

    Reference interface: torchft/process_group.py:109-166 (configure /
    allreduce / allgather / broadcast / size).
    """

    @abstractmethod
    def configure(
        self,
        store_addr: str,
        rank: int,
        world_size: int,
        regions: Optional[Sequence[str]] = None,
        hosts: Optional[Sequence[str]] = None,
    ) -> None:
        """(Re)builds the communicator for a new membership. ``store_addr``
        is ``host:port/prefix`` with a prefix unique to the quorum.

        ``regions`` (optional): one topology label per rank — the quorum's
        region map. Backends that understand topology (the host ring)
        compile it into a two-tier schedule when every member is labeled
        and >= 2 regions are present; every other backend accepts and
        ignores it (the kwarg is part of the reconfigure contract so the
        manager can hand the map to whichever plane it drives).

        ``hosts`` (optional): one host label per rank — the quorum's host
        map (``TORCHFT_HOST``, default hostname). The host ring groups
        members sharing a (region, host) pair into the SHARED-MEMORY
        intra-host ring tier (loopback TCP under ``TORCHFT_HC_SHM=0``);
        every other backend accepts and ignores it."""

    def hier_capable(self) -> bool:
        """Whether the LAST configure built a topology-aware
        (hierarchical) schedule — a region map with >= 2 distinct labels
        and/or a host map grouping >= 2 co-hosted members reached a
        backend that compiles one. Backends without the capability return
        False; callers feature-detect (the plan_hier probe candidate's
        sentinel discipline rides this)."""
        return False

    def allreduce_hier(
        self,
        tree: Any,
        op: ReduceOp = ReduceOp.SUM,
        divisor: Optional[float] = None,
        wire: Optional[str] = None,
    ) -> Work:
        """Like :meth:`allreduce` but over the TWO-TIER schedule (intra-
        region reduce-scatter -> intra allgather -> inter-region ring
        among one leader per region -> intra broadcast): the slow
        inter-region links carry (L-1)/L of the payload per ring phase
        per LEADER instead of 2*(W-1)/W per MEMBER. ``wire`` selects the
        inter hop's encoding only (``None`` | ``"bf16"`` | ``"q8"``;
        intra stays full precision — quantization noise is paid once, on
        the link that needs it). Results are bit-identical across members
        and across runs; the summation ORDER differs from the flat ring
        (two-tier reduction tree), so values match the flat result at the
        accumulation-reordering tolerance class, not bit-for-bit. Raises
        when the cohort has no usable region map (callers under the
        managed discipline see the error latched — the sentinel path)."""
        raise NotImplementedError(
            f"{type(self).__name__} has no two-tier schedule"
        )

    @abstractmethod
    def allreduce(
        self,
        tree: Any,
        op: ReduceOp = ReduceOp.SUM,
        divisor: Optional[float] = None,
        wire: Optional[str] = None,
    ) -> Work:
        """Reduces a pytree of arrays across the group; result pytree has the
        same structure/dtypes. Bit-identical on every rank.

        ``divisor`` (SUM only) divides the reduced result before it returns
        — the manager's num_participants average, applied host-side where
        the data already is, so no extra device dispatch or jit program is
        needed. ``op=AVG`` is equivalent to SUM with divisor=world_size.

        ``wire="q8"`` (SUM/AVG only): ship int8-quantized chunks with
        per-chunk f32 scales through the ring, dequant-accumulating per
        hop — ~4x fewer wire bytes than f32, CONSTANT in world size
        (unlike a quantized allgather's O(world) traffic). The result is
        lossy at the int8 quantization class; callers doing error
        feedback should treat the RETURNED tree as what was shipped.
        Implementations without a quantized wire may raise for it."""

    # Planned ops: not abstract — backends without a persistent native
    # plan keep working; callers feature-detect by catching
    # NotImplementedError (the adaptive DDP mode does exactly that).
    def plan_allreduce(
        self,
        tree: Any,
        op: ReduceOp = ReduceOp.SUM,
        divisor: Optional[float] = None,
        wire: Optional[str] = None,
        device_pack: Optional[bool] = None,
        hier: bool = False,
    ) -> Work:
        """Like :meth:`allreduce` (SUM/AVG only) but through a persistent
        precompiled comm plan: the leaf->bucket layout, dtype casts, wire
        encoding and staging buffers are compiled once per tree signature
        and each step is a single GIL-released native call — no per-step
        ``tree_flatten -> astype -> concatenate -> tobytes`` Python work
        on the gradient hot path. Results are bit-identical to the
        legacy managed path. ``wire``: ``None`` ships native dtypes,
        ``"bf16"`` rounds f32 leaves to bfloat16 on the wire, ``"q8"``
        ships int8 ring chunks, ``"q8ef"`` adds the per-leaf int8
        quantization with error feedback (the carry persists inside the
        plan; see :meth:`plan_reset_feedback`). ``device_pack``
        (True/False/None = ``TORCHFT_DEVICE_PACK``) moves the wire
        encoding onto the accelerator where supported, so the
        device->host leg costs wire bytes instead of f32 bytes —
        results stay bit-identical, backends without the capability
        host-pack. ``hier`` runs the plan over the TWO-TIER schedule
        (requires a hier-capable configure — see
        :meth:`allreduce_hier`): the wire then applies at the leader's
        inter-region hop only, staging and the intra tier stay native
        width, and ``q8ef``'s error-feedback carry refines each REGION's
        contribution at its leader."""
        raise NotImplementedError(
            f"{type(self).__name__} has no persistent comm plans"
        )

    def plan_reset_feedback(self) -> None:
        """Zeroes the error-feedback carry of every cached ``q8ef`` plan
        (no-op for backends without plans): call on heal/abort — a
        recovered member must not carry a residual from its abandoned
        trajectory."""

    # Sharded split ops: not abstract — backends whose transport has no
    # reduce-scatter boundary to expose (XLA's in-program psum is already
    # bandwidth-optimal in-chip) keep working; callers feature-detect by
    # catching NotImplementedError.
    def reduce_scatter(
        self,
        tree: Any,
        op: ReduceOp = ReduceOp.SUM,
        divisor: Optional[float] = None,
        wire: Optional[str] = None,
    ) -> Work:
        """Reduces a pytree but stops at the reduce-scatter boundary: the
        result is a :class:`TreeShard` holding only the ~1/world_size of
        the flat-packed reduction this rank owns. Composing it with
        :meth:`allgather_into` at the same wire dtype is bit-identical to
        :meth:`allreduce`; updating the shard BEFORE the allgather is the
        sharded-weight-update schedule (PAPERS.md #1) that skips the
        redundant full-tree return traffic. ``divisor``/``op``/``wire``
        as in :meth:`allreduce` (``wire="q8"`` reduces a single f32 group
        over the quantized ring; the returned shard is full f32 — the
        fused op's lossy phase-2 quantization never happens)."""
        raise NotImplementedError(
            f"{type(self).__name__} has no sharded split ops"
        )

    def allgather_into(
        self, shard: "TreeShard", wire: Optional[str] = None
    ) -> Work:
        """Gathers every rank's (possibly updated) :class:`TreeShard` back
        into the full pytree — phase 2 of the ring, run on current values.
        ``wire="bf16"`` ships f32 groups as bfloat16 (half the bytes; all
        members decode identical bf16 words, so results stay bit-identical
        across ranks). All ranks must pass shards from the same logical
        reduce_scatter (same layout)."""
        raise NotImplementedError(
            f"{type(self).__name__} has no sharded split ops"
        )

    # Sharded PLAN ops (the per-step ZeRO hot path): not abstract —
    # callers feature-detect by catching NotImplementedError, exactly
    # like the fused plan path.
    def plan_reduce_scatter(
        self,
        tree: Any,
        op: ReduceOp = ReduceOp.SUM,
        divisor: Optional[float] = None,
        wire: Optional[str] = None,
        ag_wire: Optional[str] = None,
    ) -> Work:
        """Like :meth:`reduce_scatter` (SUM/AVG only) but through a
        persistent precompiled SHARDED comm plan: leaf layout, staging and
        the stripe partition are compiled once per (signature, wires) and
        the grad leg runs as one GIL-released native call. The returned
        :class:`TreeShard` carries the plan, and
        :meth:`plan_allgather_into` MUST receive it back — both legs share
        the plan's partition, so shard boundaries are one arithmetic fact.
        ``wire`` encodes the grad leg (``None``/``"bf16"``/``"q8"``; the
        owned shard lands full f32 regardless); ``ag_wire`` pre-declares
        the param leg's encoding (``None``/``"bf16"``), baked into the
        plan so a native-gathering member and a bf16-gathering one error
        apart at the header. f32 leaves only — the shard layout is one
        flat f32 group (keep f32 master weights, the same constraint the
        sharded DiLoCo path enforces)."""
        raise NotImplementedError(
            f"{type(self).__name__} has no sharded comm plans"
        )

    def plan_allgather_into(
        self, shard: "TreeShard", wire: Optional[str] = None
    ) -> Work:
        """Param leg of the sharded plan: gathers every rank's (updated)
        shard back into the full pytree through the plan that produced it
        (:meth:`plan_reduce_scatter`). ``wire`` must match the plan's
        ``ag_wire`` (``"bf16"``: every member adopts the identical decoded
        words, so gathered params stay bit-identical across the cohort)."""
        raise NotImplementedError(
            f"{type(self).__name__} has no sharded comm plans"
        )

    @abstractmethod
    def allgather(self, tree: Any) -> Work:
        """Gathers each rank's pytree; result is a list of pytrees in rank
        order (all ranks must pass identical structures and shapes)."""

    @abstractmethod
    def broadcast(self, tree: Any, root: int = 0) -> Work:
        """Broadcasts root's pytree to all ranks."""

    @abstractmethod
    def barrier(self) -> Work:
        ...

    @abstractmethod
    def size(self) -> int:
        ...

    @abstractmethod
    def rank(self) -> int:
        ...

    def abort(self) -> None:
        """Unblocks in-flight ops with an error (safe from any thread)."""

    def shutdown(self) -> None:
        ...


# Cap on the per-stripe timing readback; matches tft::kMaxStripes.
_MAX_STRIPES = 64

# Mirrors native kMinStripeBytes / effective_stripes (collectives.cc): the
# payload-derived stripe partition. Python computes it so a sharded sync
# can PIN one partition across a q8 reduce-scatter (1 wire byte/element)
# and a bf16 parameter allgather (2 bytes/element) — left to the native
# auto-derivation, the two ops would partition the payload differently and
# the shard would scatter to the wrong chunk boundaries. The
# decomposed-vs-fused bit-identity tests pin this mirror against native.
_MIN_STRIPE_BYTES = 64 << 10


def _effective_stripes(payload_bytes: int, configured: int) -> int:
    return max(1, min(configured, max(1, payload_bytes // _MIN_STRIPE_BYTES)))


def _as_numpy(leaf: Any) -> np.ndarray:
    """Host copy of a leaf (device→host transfer for jax arrays)."""
    return np.asarray(leaf)


def _is_jax_array(leaf: Any) -> bool:
    import jax

    return isinstance(leaf, jax.Array)


class _DevicePacker:
    """Jitted pack/unpack of a fixed tree signature into ONE flat buffer per
    accumulation dtype.

    Per-transfer latency dominates device↔host links (PCIe DMA setup; far
    worse on tunneled devices), so shipping ~100 gradient leaves
    individually costs ~100 round-trips. Packing on-device via a jitted
    concatenate makes the whole pytree cross as one transfer per dtype
    group, and unpacking (split + reshape + cast back) stays on-device too.
    """

    def __init__(
        self,
        leaves: Sequence[Any],
        exact_dtypes: bool = False,
        force_f32: bool = False,
    ) -> None:
        """``exact_dtypes``: group by each leaf's own dtype with no
        casting — for BYTE-PRESERVING ops (allgather ships opaque bytes,
        e.g. int8-quantized payloads, where upcasting to an accumulation
        dtype would 4x the wire). ``force_f32``: ONE f32 group for the
        whole tree — the quantized (q8) ring reduces a single flat f32
        buffer. Reduction ops keep the default accumulation-dtype
        grouping (the ring arithmetic needs native dtypes)."""
        import jax
        import jax.numpy as jnp

        assert not (exact_dtypes and force_f32)
        self.sig = tuple((l.shape, np.dtype(l.dtype)) for l in leaves)
        groups: dict = {}
        for i, (_, dt) in enumerate(self.sig):
            if force_f32:
                acc = np.dtype(np.float32)
            elif exact_dtypes:
                acc = dt
            else:
                acc = dt if dt in _NATIVE_DTYPES else np.dtype(np.float32)
            groups.setdefault(acc, []).append(i)
        self.groups = groups
        sig = self.sig

        def pack(ls):
            return {
                str(acc): jnp.concatenate(
                    [ls[i].ravel().astype(acc) for i in idxs]
                )
                for acc, idxs in groups.items()
            }

        def unpack(bufs):
            out = [None] * len(sig)
            for acc, idxs in groups.items():
                buf = bufs[str(acc)]
                off = 0
                for i in idxs:
                    shape, dt = sig[i]
                    n = int(np.prod(shape)) if shape else 1
                    out[i] = buf[off : off + n].reshape(shape).astype(dt)
                    off += n
            return out

        self.pack = jax.jit(pack)
        self.unpack = jax.jit(unpack)


# Python wire names -> native PlanWire codes (collectives.h).
_PLAN_WIRES = {None: 0, "bf16": 1, "q8": 2, "q8ef": 3}

# Python wire names -> native HierWire codes (the INTER hop's encoding of
# the two-tier schedule; intra always rides native dtypes).
_HIER_WIRES = {None: 0, "bf16": 1, "q8": 2}

# Wires the DEVICE pack (Pallas kernels emitting the wire encoding on the
# accelerator) supports. Plain "q8" is deliberately absent: its host-pack
# contract ships RAW f32 to the quantized ring, and quantizing at the
# device boundary would change the numerics — callers wanting the device
# quantize use "q8ef" (what the DDP q8 mode maps to anyway).
_DEVICE_PACK_WIRES = (None, "bf16", "q8ef")

# Bytes of the native per-op header exchange (check_op_header's struct:
# magic, kind, count, dtype, op — collectives.cc).
_OP_HEADER_BYTES = 24


def _resolve_device_pack_setting(setting: Any) -> Optional[bool]:
    """ONE parser for the TORCHFT_DEVICE_PACK knob, shared by every layer
    (HostCollectives, PipelinedDDP, AdaptiveDDP): maps a ctor/env setting
    to True (pack on device) / False (host) / None (backend auto).
    ``None`` input reads the env; raises ValueError on junk — callers
    invoke this EAGERLY so a typo'd knob fails loudly instead of latching
    per step in the managed dispatch."""
    if setting is None:
        setting = os.environ.get("TORCHFT_DEVICE_PACK", "auto")
    if isinstance(setting, str):
        try:
            return {"on": True, "off": False, "auto": None}[setting]
        except KeyError:
            raise ValueError(
                f"TORCHFT_DEVICE_PACK={setting!r} (want auto|on|off)"
            ) from None
    return bool(setting)


def _q8_wire_overhead(eff: int, world: int, phases: int = 2) -> int:
    """Bytes the q8 wire ships beyond its int8 payload: one f32 scale per
    (stripe, ring chunk) per quantized phase — the fused allreduce runs
    two (reduce-scatter + allgather), reduce_scatter one — plus the
    per-op header exchange. Counted so compression ratios are honest
    (`wire_bytes: count` alone pretends the sidecar is free)."""
    return 4 * eff * max(world, 1) * phases + _OP_HEADER_BYTES


def _plan_groups(
    sig: Sequence[Tuple[Any, Any]], wire: Optional[str]
) -> List[Tuple[Any, List[int]]]:
    """leaf -> group assignment of a comm plan, replicating native
    plan_build EXACTLY (first-appearance order of the group dtype over
    leaves in signature order) — the device packer and the prepacked
    execute index groups positionally, so the two layouts must be one.
    Returns [(group np.dtype, [leaf indices])]; raises KeyError on a
    signature the plan path cannot take (the callers' fallback signal)."""
    f32 = np.dtype(np.float32)
    groups: List[Tuple[Any, List[int]]] = []
    for i, (_, dt) in enumerate(sig):
        if wire in ("q8", "q8ef"):
            if dt not in (f32, _BF16):
                raise KeyError(dt)
            gdt = f32
        else:
            if dt not in _NATIVE_DTYPES:
                raise KeyError(dt)
            gdt = _BF16 if (wire == "bf16" and dt == f32) else dt
        for g in groups:
            if g[0] == gdt:
                g[1].append(i)
                break
        else:
            groups.append((gdt, [i]))
    return groups


class _DeviceWirePacker:
    """Pallas-kernel pack of a fixed tree signature into the WIRE
    encoding, ON DEVICE (torchft_tpu.ops.quantize_kernels), emitting the
    pre-packed per-group buffers a prepacked CommPlan decodes:

    - ``wire="q8ef"``: per-leaf int8 EF quantization — the codes
      concatenate into the plan's single f32 group layout, the per-leaf
      scales form the sidecar, and the error-feedback carry lives HERE as
      device-resident f32 arrays that never cross the link. ~1 byte per
      element crosses d2h instead of 4.
    - ``wire="bf16"``: f32 leaves concatenate and cast to bf16 on device
      (2 bytes/element d2h); other dtypes pack natively.
    - ``wire=None``: the plain concat pack (native bytes — no byte win,
      but one transfer per dtype group instead of one per leaf).

    The group layout replicates native plan_build positionally
    (_plan_groups), which is what lets plan_execute_pre skip its pack
    stage. The quantization arithmetic is the FMA-free mirror of the
    native EF (the kernels' tested contract), so device-packed staging is
    bit-identical to host-packed staging and mixed rings interoperate."""

    def __init__(self, leaves: Sequence[Any], wire: Optional[str]) -> None:
        import jax
        import jax.numpy as jnp

        from .ops import quantize_kernels as qk

        if wire not in _DEVICE_PACK_WIRES:
            raise KeyError(wire)
        self.wire = wire
        self.sig = tuple((l.shape, np.dtype(l.dtype)) for l in leaves)
        self.groups = _plan_groups(self.sig, wire)  # KeyError -> no packer
        sig = self.sig
        groups = self.groups
        f32 = np.dtype(np.float32)

        if wire == "q8ef":
            ((_, idxs),) = groups  # q8 plans are a single f32 group
            self.residuals: Optional[List[Any]] = [
                jnp.zeros(sig[i][0], jnp.float32) for i in idxs
            ]

            def pack(ls: Sequence[Any], residuals: Sequence[Any]):
                qs, scales, new_res = [], [], []
                for k, i in enumerate(idxs):
                    q, s, r = qk.quantize_q8_ef(
                        ls[i].astype(jnp.float32), residuals[k]
                    )
                    qs.append(q.ravel())
                    scales.append(s.reshape(1))
                    new_res.append(r)
                return [jnp.concatenate(qs)], [jnp.concatenate(scales)], new_res
        else:
            self.residuals = None

            def pack(ls: Sequence[Any], residuals: Sequence[Any]):
                payloads = []
                for gdt, idxs in groups:
                    if gdt == _BF16 and any(sig[i][1] != _BF16 for i in idxs):
                        # f32 (or mixed) sources: concat in f32, one cast
                        # kernel per group (bf16->f32->bf16 round-trips
                        # exactly, so native-bf16 leaves are unharmed)
                        buf = jnp.concatenate(
                            [ls[i].astype(f32).ravel() for i in idxs]
                        )
                        payloads.append(qk.cast_bf16(buf))
                    else:
                        payloads.append(jnp.concatenate(
                            [ls[i].astype(gdt).ravel() for i in idxs]
                        ))
                return payloads, [], []

        self._pack = jax.jit(pack)

    def pack_step(self, leaves: Sequence[Any]):
        """(payload arrays, scale arrays, residual rollover) — one entry
        per plan group (scales empty off the q8 wires). Advances the
        device-resident EF carry."""
        payloads, scales, new_res = self._pack(
            leaves, self.residuals if self.residuals is not None else []
        )
        if self.residuals is not None:
            self.residuals = new_res
        return payloads, scales

    def reset_feedback(self) -> None:
        """Zeroes the device-resident EF carry (the heal/abort
        discipline, same contract as the native plan carry)."""
        if self.residuals is not None:
            import jax.numpy as jnp

            self.residuals = [jnp.zeros_like(r) for r in self.residuals]


class _CommPlan:
    """Python handle for one native CommPlan.

    Everything a step needs is allocated HERE, once: the input pointer
    array, and two alternating sets of output leaf arrays (a caller may
    still hold step k's result while step k+1 executes — PipelinedDDP's
    one-step overlap — so outputs double-buffer; a result older than two
    executes is clobbered). Steady-state execute therefore performs zero
    Python-side staging allocation: the only per-step Python work is
    writing leaf pointers.
    """

    def __init__(self, handle: Any, sig: Sequence[Any], treedef: Any,
                 wire: Optional[str], stripes: int = 1, world: int = 1,
                 prepacked: bool = False, hier: bool = False) -> None:
        self.treedef = treedef
        self.sig = tuple(sig)
        self.wire = wire
        self.prepacked = prepacked
        self.hier = hier
        n = len(self.sig)
        counts = [int(np.prod(s)) if s else 1 for s, _ in self.sig]
        # KeyError on a non-native dtype: the caller treats it as
        # "unsupported signature" and falls back to the legacy path.
        codes = [_NATIVE_DTYPES[dt] for _, dt in self.sig]
        assert not (prepacked and hier)
        build = (
            _lib.tft_plan_build_hier if hier
            else _lib.tft_plan_build_pre if prepacked
            else _lib.tft_plan_build
        )
        plan_id = build(
            handle,
            (ctypes.c_int64 * n)(*counts),
            (ctypes.c_int32 * n)(*codes),
            n,
            _PLAN_WIRES[wire],
        )
        if plan_id < 0:
            _check(2)
        self.plan_id = plan_id
        self._handle = handle
        self.in_ptrs = (ctypes.c_void_p * n)()
        if prepacked:
            # Per-GROUP wire payload + scale-sidecar pointer arrays, in
            # the native plan's group order (_plan_groups replicates it).
            ng = len(_plan_groups(self.sig, wire))
            self.group_in = (ctypes.c_void_p * ng)()
            self.group_aux = (ctypes.c_void_p * ng)()
        self.out_sets: List[List[np.ndarray]] = []
        self.out_ptrs: List[Any] = []
        for _ in range(2):
            outs = [np.empty(s, dt) for s, dt in self.sig]
            self.out_sets.append(outs)
            self.out_ptrs.append(
                (ctypes.c_void_p * n)(*[o.ctypes.data for o in outs])
            )
        self.flip = 0
        self.execs = 0
        self.bytes = sum(
            c * np.dtype(dt).itemsize for c, (_, dt) in zip(counts, self.sig)
        )
        if wire in ("q8", "q8ef"):
            # int8 codes + the per-(stripe, ring chunk) scale sidecar and
            # the op header — the honest quantized-wire bill (q8 plans
            # pack ONE f32 group, so its stripe partition is the op's)
            total = sum(counts)
            eff = _effective_stripes(total, stripes)
            self.wire_bytes = total + _q8_wire_overhead(eff, world)
        elif wire == "bf16":
            self.wire_bytes = sum(
                c * (2 if np.dtype(dt) == np.dtype(np.float32)
                     else np.dtype(dt).itemsize)
                for c, (_, dt) in zip(counts, self.sig)
            )
        else:
            self.wire_bytes = self.bytes


class _ShardedPlan:
    """Python handle for one native SHARDED CommPlan (per-step ZeRO).

    Like :class:`_CommPlan`, everything a step needs is allocated once:
    the input pointer array, two alternating f32 shard buffers for the
    grad leg (the caller may still hold step k's shard while step k+1
    reduces — so shards double-buffer like plan outputs), and two
    alternating full-leaf output sets for the param leg.
    """

    def __init__(self, handle: Any, sig: Sequence[Any], treedef: Any,
                 wire: Optional[str], ag_wire: Optional[str],
                 stripes: int = 1, world: int = 1) -> None:
        f32 = np.dtype(np.float32)
        if any(np.dtype(dt) != f32 for _, dt in sig):
            # The callers' fallback signal, like _plan_groups.
            raise KeyError("sharded plans take f32 leaves only")
        self.treedef = treedef
        self.sig = tuple(sig)
        self.wire = wire
        self.ag_wire = ag_wire
        n = len(self.sig)
        counts = [int(np.prod(s)) if s else 1 for s, _ in self.sig]
        codes = [_NATIVE_DTYPES[np.dtype(dt)] for _, dt in self.sig]
        plan_id = _lib.tft_plan_build_sharded(
            handle,
            (ctypes.c_int64 * n)(*counts),
            (ctypes.c_int32 * n)(*codes),
            n,
            _PLAN_WIRES[wire],
            _PLAN_WIRES[ag_wire],
        )
        if plan_id < 0:
            _check(2)
        self.plan_id = plan_id
        self._handle = handle
        meta = (ctypes.c_int64 * 3)()
        _check(_lib.tft_plan_sharded_meta(handle, plan_id, meta))
        self.shard_count = int(meta[0])
        self.eff = int(meta[1])
        self.total = int(meta[2])
        self.in_ptrs = (ctypes.c_void_p * n)()
        self.shard_sets = [
            np.empty(self.shard_count, np.float32) for _ in range(2)
        ]
        self.shard_flip = 0
        self.out_sets: List[List[np.ndarray]] = []
        self.out_ptrs: List[Any] = []
        for _ in range(2):
            outs = [np.empty(s, dt) for s, dt in self.sig]
            self.out_sets.append(outs)
            self.out_ptrs.append(
                (ctypes.c_void_p * n)(*[o.ctypes.data for o in outs])
            )
        self.flip = 0
        self.execs = 0
        self.bytes = self.total * 4
        # Per-leg wire bills (the honest accounting satellite): the grad
        # leg runs ONE ring phase at the rs wire, the param leg one at
        # the ag wire.
        if wire == "q8":
            self.rs_wire_bytes = self.total + _q8_wire_overhead(
                self.eff, world, phases=1
            )
        elif wire == "bf16":
            self.rs_wire_bytes = self.total * 2
        else:
            self.rs_wire_bytes = self.total * 4
        self.ag_wire_bytes = self.total * (2 if ag_wire == "bf16" else 4)


class OpStatsMixin:
    """Per-op phase-timing recorder shared by every data-plane backend
    (host ring, XLA, isolated XLA): the accounting contract AdaptiveDDP's
    probe comparisons and the diagnosis tooling rely on is that EVERY
    backend's ops drain through one ``pop_op_stats`` with the same core
    keys — ``op``, ``bytes`` (payload) and ``d2h_bytes`` (what actually
    crossed the device link) — plus backend-specific phase timings."""

    _op_stats: List[dict]

    def _record_op_stats(self, stats: dict) -> None:
        if not hasattr(self, "_op_stats"):
            self._op_stats = []
        self._op_stats.append(stats)
        # Bounded: diagnostics, not a log. 256 keeps a full per-step
        # breakdown window alive — at one gradient op + a handful of
        # control ops per step, 64 silently dropped the early entries
        # before the caller's median ever saw them.
        del self._op_stats[:-256]

    def pop_op_stats(self) -> List[dict]:
        """Drains the recorded per-op phase timings (seconds). Core keys
        on every backend: ``op``, ``bytes`` (the logical payload) and
        ``d2h_bytes`` (bytes that crossed the DEVICE link — the number
        that tells a slow transfer from a slow wire). Host-ring entries
        additionally carry ``wire_bytes``/``chunks``/``stripe_s`` and the
        per-bucket plan breakdown; XLA-path entries carry the
        stack/dispatch/localize split; isolated entries add the
        child-side wall and reduction path."""
        out, self._op_stats = getattr(self, "_op_stats", []), []
        for st in out:
            # Plan entries carry their native per-bucket stats as a raw
            # JSON string (decoding per step would put a parse on the
            # zero-Python hot path); decode at drain time.
            raw = st.pop("_buckets_json", None)
            if raw is not None:
                st["buckets"] = json.loads(raw).get("buckets", [])
        return out


class HostCollectives(OpStatsMixin, Collectives):
    """Deterministic TCP ring collectives (native C++), the Gloo role.

    One contiguous buffer per dtype group is reduced per op — leaves are
    packed ON DEVICE (jitted concatenate, one device↔host transfer per
    dtype group) when the tree is jax arrays, host-side otherwise — so a
    whole gradient pytree costs a single ring pass per dtype (the bucketing
    the reference gets from DDP's reducer).
    """

    def __init__(
        self,
        timeout: timedelta = timedelta(seconds=60),
        connect_timeout: timedelta = timedelta(seconds=60),
        pipeline_chunks: Optional[int] = None,
        pipeline_min_bytes: int = 4 << 20,
        stripes: Optional[int] = None,
        stripes_inter: Optional[int] = None,
        wire_crc: Optional[bool] = None,
    ) -> None:
        """``pipeline_chunks`` > 1 splits large device-packed buffers so
        device->host DMA, the TCP ring, and host->device upload overlap
        (chunk i rides the ring while chunk i+1 is still downloading and
        chunk i-1 re-uploads — and the pipeline runs ACROSS dtype buckets,
        not just within one packed buffer). Buffers under
        ``pipeline_min_bytes`` take the single-shot path — per-transfer
        latency would beat the overlap. Chunk boundaries depend only on
        size, so results stay bit-identical across ranks and against the
        unchunked path.

        Default: env ``TORCHFT_HC_PIPELINE_CHUNKS`` (else 4). Set it to 1
        on hosts whose device runtime wedges in-flight transfers under
        overlapping async dispatch (observed on tunneled/proxied device
        sessions) — every member of a ring must use the same value.

        ``stripes`` > 1 spreads every ring op over that many parallel TCP
        connections per neighbor (contiguous payload sub-ranges, one
        reducer thread per stripe) — a single TCP connection is
        window-limited on high-bandwidth-delay links, so striping
        multiplies achievable cross-group throughput the way NCCL
        channels do. Default: env ``TORCHFT_HC_STRIPES`` (else 4). Every
        member of a ring must use the same value; configure() negotiates
        it through the rendezvous store (exactly like the pipeline knobs)
        and fails fast on a mismatch.

        ``stripes_inter`` is the INTER-REGION (leader) ring's parallel-
        connection count under a two-tier configure — the slow wide-area
        hop is exactly where striping pays, so it gets its own knob.
        Default: env ``TORCHFT_HC_STRIPES_INTER`` (else ``stripes``).
        Store-negotiated like the rest of the schedule knobs.

        ``wire_crc`` (default: env ``TORCHFT_WIRE_CRC``, off) puts a
        CRC32C trailer on every ring/stripe payload frame; a mismatch
        raises the typed :class:`~torchft_tpu._native.WireCorruption`
        (latched by the Manager, step discarded by the vote) instead of
        committing poisoned bytes — the one failure mode the vote alone
        cannot catch. All members must agree: the knob rides the same
        store-negotiated fingerprint as the stripes, and the ring hello
        carries the frame format so a drifted member fails at connect.
        Off, the wire format is byte-identical to the pre-CRC protocol
        (un-upgraded peers interop) and the hot path pays one branch."""
        self._handle = _lib.tft_hc_create()
        self._timeout = timeout
        self._connect_timeout = connect_timeout
        if pipeline_chunks is None:
            pipeline_chunks = int(
                os.environ.get("TORCHFT_HC_PIPELINE_CHUNKS", "4")
            )
        self._pipeline_chunks = max(int(pipeline_chunks), 1)
        self._pipeline_min_bytes = int(pipeline_min_bytes)
        if stripes is None:
            stripes = int(os.environ.get("TORCHFT_HC_STRIPES", "4"))
        self._stripes = min(max(int(stripes), 1), _MAX_STRIPES)
        if stripes_inter is None:
            stripes_inter = int(
                os.environ.get("TORCHFT_HC_STRIPES_INTER", "0")
            )
        # <= 0: follow the main stripe knob (resolved at configure, so
        # the negotiated string stays honest about the effective value).
        self._stripes_inter = min(int(stripes_inter), _MAX_STRIPES)
        if wire_crc is None:
            wire_crc = os.environ.get("TORCHFT_WIRE_CRC", "").lower() in (
                "1", "on", "true",
            )
        self._wire_crc = bool(wire_crc)
        self._world_size = 0
        self._rank = -1
        # One thread: collectives must issue in submission order.
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="host_collectives"
        )
        self._shutdown = False
        self._packers: dict = {}
        # Device WIRE packers (Pallas quantize/cast on the accelerator)
        # keyed like plans; a None value marks a signature/wire the
        # device pack cannot serve (host pack serves it instead). These
        # hold the device-resident q8 EF carries, so plan_reset_feedback
        # zeroes them alongside the native plan carries. Survive
        # configure(): the pack is ring-geometry-free (pure per-leaf
        # encoding), unlike the plans themselves.
        self._dev_packers: dict = {}
        # Persistent comm plans keyed by (wire, treedef, signature); a
        # None value marks a signature the plan path cannot take (the
        # legacy path serves it). Invalidated wholesale on configure() —
        # the native layer drops its side at the same moment.
        self._plans: dict = {}
        # Per-op phase timings recorded by the device-packed paths (see
        # pop_op_stats): on tunneled device runtimes the d2h leg can cost
        # 10x the ring leg, and nothing else distinguishes them.
        self._op_stats: List[dict] = []

    def _last_stripe_seconds(self) -> List[float]:
        """Per-stripe wall times (s) of the last native ring op; safe only
        on the op-executor thread (which is where all ring calls run)."""
        buf = (ctypes.c_int64 * _MAX_STRIPES)()
        n = _lib.tft_hc_last_stripe_ns(self._handle, buf, _MAX_STRIPES)
        return [buf[i] / 1e9 for i in range(min(n, _MAX_STRIPES))]

    # pop_op_stats: OpStatsMixin. Host-ring entries record ``pack``
    # (jitted concat dispatch), ``d2h`` (the blocking device→host read),
    # ``ring`` (the native TCP op), ``h2d`` (result upload + unpack
    # DISPATCH — jax uploads asynchronously, so the actual transfer
    # completes under the caller's next use/drain and is charged there),
    # ``wire_bytes`` where the TCP wire ships a different encoding, and
    # per-bucket ``buckets`` with per-stripe ring wall times.

    # -- lifecycle --

    def configure(
        self,
        store_addr: str,
        rank: int,
        world_size: int,
        regions: Optional[Sequence[str]] = None,
        hosts: Optional[Sequence[str]] = None,
    ) -> None:
        # Abort synchronously so a wedged op can't block the executor, then
        # run the (blocking) rendezvous on the op thread to keep ordering.
        _lib.tft_hc_abort(self._handle)
        # The region and host maps are part of the schedule contract (they
        # decide which tiers exist and who leads them); normalize them
        # here so the negotiated fingerprint below and the native build
        # see one form.
        region_list: List[str] = (
            [str(r) for r in regions] if regions else []
        )
        if region_list and len(region_list) != world_size:
            raise ValueError(
                f"regions must carry one label per rank "
                f"({len(region_list)} labels for world_size {world_size})"
            )
        host_list: List[str] = [str(h) for h in hosts] if hosts else []
        if host_list and len(host_list) != world_size:
            raise ValueError(
                f"hosts must carry one label per rank "
                f"({len(host_list)} labels for world_size {world_size})"
            )
        stripes_inter = (
            self._stripes_inter if self._stripes_inter > 0 else self._stripes
        )
        # The shm knobs are schedule-relevant for co-hosted members (the
        # producer and consumer of one ring must agree on transport and
        # capacity), so they ride the negotiated fingerprint like every
        # other knob. Snapshotted here; the native side re-reads the env
        # at configure, so the two stay in step.
        shm_on = os.environ.get("TORCHFT_HC_SHM", "").lower() not in (
            "0", "off", "false",
        )
        shm_ring = max(
            int(os.environ.get("TORCHFT_HC_SHM_RING_BYTES", str(1 << 20))),
            4096,
        )

        def do_configure() -> None:
            # The pipeline parameters are part of the ring's op schedule
            # (they decide how many native allreduce calls one logical
            # allreduce issues, and the wire has no per-op framing), so
            # every member must agree — validate against rank 0's via the
            # rendezvous store and fail fast instead of desyncing. A solo
            # member has no peers (and possibly no real store) to check.
            # The two-tier inputs (inter stripes + the region map) ride
            # the same fingerprint: a member with a drifted map would
            # otherwise build a different topology and wedge mid-op.
            if world_size > 1:
                hostport, _, prefix = store_addr.partition("/")
                store = _native.StoreClient(
                    hostport, connect_timeout=self._connect_timeout
                )
                # The CRC token is appended ONLY when on: a CRC-off fleet
                # keeps the exact pre-CRC fingerprint, so un-upgraded
                # peers interop; a mixed on/off pair mismatches here with
                # a descriptive error (and would fail at the hello
                # anyway — this is the friendlier first line of defense).
                mine = (
                    f"{self._pipeline_chunks}:{self._pipeline_min_bytes}"
                    f":{self._stripes}:{stripes_inter}"
                    f":{','.join(region_list)}"
                    + (":crc1" if self._wire_crc else "")
                    # Appended ONLY when the host map is USABLE (every
                    # rank labeled — the native hosts_labeled rule): a
                    # partially labeled map (mixed-version fleet, some
                    # members pre-host-PR) builds no host tier, so the
                    # knobs are schedule-irrelevant there and appending
                    # them would break interop with un-upgraded peers
                    # for nothing. Fully unlabeled fleets keep the exact
                    # pre-host fingerprint.
                    + (
                        f":hosts={','.join(host_list)}"
                        f":shm{1 if shm_on else 0}:{shm_ring}"
                        if host_list and all(host_list) else ""
                    )
                )
                key = f"{prefix}/pipecfg" if prefix else "pipecfg"
                if rank == 0:
                    store.set(key, mine.encode())
                else:
                    theirs = store.get(
                        key, timeout=self._connect_timeout
                    ).decode()
                    if theirs != mine:
                        raise RuntimeError(
                            f"pipeline config mismatch: rank {rank} has "
                            f"{mine}, rank 0 has {theirs} — all ring members "
                            "must construct HostCollectives with the same "
                            "pipeline_chunks / pipeline_min_bytes / stripes "
                            "/ stripes_inter and see the same region map"
                        )
            _lib.tft_hc_set_wire_crc(self._handle, 1 if self._wire_crc else 0)
            _check(
                _lib.tft_hc_configure_hier(
                    self._handle,
                    store_addr.encode(),
                    rank,
                    world_size,
                    _ms(self._connect_timeout),
                    self._stripes,
                    stripes_inter,
                    json.dumps(region_list).encode()
                    if region_list else b"",
                    json.dumps(host_list).encode()
                    if host_list else b"",
                )
            )
            # Assign on the op thread: ops queued after this configure see
            # the new size, earlier ones the old — never a mix.
            self._rank = rank
            self._world_size = world_size
            # The native side just dropped every plan (their layout bakes
            # in the old ring); drop the Python handles in the same
            # ordered position so no queued op can execute a stale id.
            self._plans = {}
            # Device packers survive (their jitted encode is geometry-
            # free) but their EF carries zero — a host-packed member's
            # carry died with its plan just now, and the two modes must
            # stay bit-identical across reconfigures.
            for packer in self._dev_packers.values():
                if packer is not None:
                    packer.reset_feedback()

        self._executor.submit(do_configure).result()

    def abort(self) -> None:
        _lib.tft_hc_abort(self._handle)

    def prewarm(self, tree: Any = None) -> None:
        """Shadow-mode warm-up for hot-spare standbys: spins up the op
        executor thread and, given a ``tree`` shaped like the payload the
        promoted worker will sync (its gradient pytree), jits and runs
        the device pack/unpack programs for that signature — so the first
        post-promotion allreduce pays neither thread start nor packer
        compile. NO network is touched (the ring only exists after
        ``configure``), which is what makes it safe for a parked standby
        that must not be visible to the quorum."""

        def warm() -> None:
            if tree is None:
                return
            leaves, treedef = _flatten(tree)
            if not leaves or not all(_is_jax_array(l) for l in leaves):
                return
            import jax

            key = (treedef, tuple((l.shape, np.dtype(l.dtype)) for l in leaves))
            packer = self._packers.get(key)
            if packer is None:
                packer = self._packers[key] = _DevicePacker(leaves)
            # Round-trip once: both executables compile (and land in the
            # persistent cache), no ring op is issued.
            jax.block_until_ready(packer.unpack(packer.pack(leaves)))

        self._submit(warm).wait()

    def shutdown(self) -> None:
        if self._shutdown:
            return
        self._shutdown = True
        _lib.tft_hc_abort(self._handle)
        self._executor.shutdown(wait=True)
        # Deterministic ring teardown (sockets, listener, shm segments):
        # named kernel resources must not live until garbage collection
        # gets around to the handle.
        _lib.tft_hc_release(self._handle)

    def __del__(self) -> None:
        handle = getattr(self, "_handle", None)
        if handle and _lib is not None:
            try:
                self.shutdown()  # aborts + drains the executor, handle intact
            except Exception:
                pass
            self._handle = None
            _lib.tft_hc_destroy(handle)

    def size(self) -> int:
        return self._world_size

    def rank(self) -> int:
        return self._rank

    # -- ops --

    def _submit(self, fn: Callable[[], Any]) -> Work:
        if self._shutdown:
            raise RuntimeError("collectives already shut down")
        return Work(self._executor.submit(fn))

    def allreduce(
        self,
        tree: Any,
        op: ReduceOp = ReduceOp.SUM,
        divisor: Optional[float] = None,
        wire: Optional[str] = None,
    ) -> Work:
        timeout_ms = _ms(self._timeout)
        if wire not in (None, "q8"):
            raise ValueError(f"unsupported wire: {wire!r}")
        if wire == "q8":
            if op == ReduceOp.AVG:
                divisor, op = float(self._world_size), ReduceOp.SUM
            if op != ReduceOp.SUM:
                raise ValueError("wire='q8' supports SUM/AVG only")
            return self._submit(
                lambda: self._allreduce_q8_sync(tree, divisor, timeout_ms)
            )
        return self._submit(
            lambda: self._allreduce_sync(tree, op, timeout_ms, divisor)
        )

    def _allreduce_q8_sync(
        self, tree: Any, divisor: Optional[float], timeout_ms: int
    ) -> Any:
        """Quantized ring SUM: the whole tree packs into ONE flat f32
        buffer (jitted on-device concat for jax leaves — one transfer per
        direction), the native ring ships int8 chunks with per-chunk
        scales, and the result unpacks to the original dtypes."""
        if self._world_size == 1:
            if divisor is not None and divisor != 1:
                import jax

                return jax.tree_util.tree_map(
                    lambda l: _divide_leaf(l, divisor)
                    if hasattr(l, "__truediv__")
                    else l,
                    tree,
                )
            return tree
        leaves, treedef = _flatten(tree)
        if not leaves:
            return tree
        all_jax = all(_is_jax_array(l) for l in leaves)
        if all_jax:
            key = (
                "q8", treedef,
                tuple((l.shape, np.dtype(l.dtype)) for l in leaves),
            )
            packer = self._packers.get(key)
            if packer is None:
                packer = self._packers[key] = _DevicePacker(
                    leaves, force_f32=True
                )
            t0 = time.perf_counter()
            buf = np.asarray(packer.pack(leaves)[str(np.dtype(np.float32))])
            if not buf.flags.writeable or not buf.flags.c_contiguous:
                buf = np.array(buf)
            d2h_s = time.perf_counter() - t0
        else:
            arrays = [_as_numpy(l) for l in leaves]
            buf = np.concatenate(
                [a.astype(np.float32, copy=False).ravel() for a in arrays]
            )
        t1 = time.perf_counter()
        _check(
            _lib.tft_hc_allreduce_q8(
                self._handle,
                buf.ctypes.data_as(ctypes.c_void_p),
                buf.size,
                timeout_ms,
            )
        )
        stripe_s = self._last_stripe_seconds()
        if divisor is not None:
            buf /= divisor
        ring_s = time.perf_counter() - t1
        if all_jax:
            import jax.numpy as jnp

            out = _unflatten(
                treedef,
                packer.unpack({str(np.dtype(np.float32)): jnp.asarray(buf)}),
            )
            self._record_op_stats({
                "op": "allreduce_q8", "bytes": buf.nbytes,
                # TCP wire ships int8 chunks + per-chunk f32 scales + the
                # op header, not the f32 device payload — the sidecar is
                # counted (one scale per stripe x ring chunk x phase) so
                # the compression ratio is honest.
                "wire_bytes": buf.size + _q8_wire_overhead(
                    _effective_stripes(buf.size, self._stripes),
                    self._world_size,
                ),
                # Host-side quantization: the device link still carried
                # the FULL f32 payload (the device-pack plan path is what
                # shrinks this).
                "d2h_bytes": buf.nbytes,
                "d2h": d2h_s, "ring": ring_s,
                "h2d": time.perf_counter() - t1 - ring_s,
                "stripe_s": stripe_s,
            })
            return out
        out_leaves = []
        offset = 0
        for a in arrays:
            n = a.size
            out_leaves.append(
                buf[offset : offset + n]
                .reshape(a.shape)
                .astype(a.dtype, copy=False)
            )
            offset += n
        return _unflatten(treedef, out_leaves)

    def _allreduce_sync(
        self,
        tree: Any,
        op: ReduceOp,
        timeout_ms: int,
        divisor: Optional[float] = None,
    ) -> Any:
        if divisor is not None and op != ReduceOp.SUM:
            raise ValueError("divisor only composes with ReduceOp.SUM")
        if self._world_size == 1:
            # Identity-ish (SUM of one member; AVG divides by 1): skip the
            # host pack/transfer entirely — device arrays never leave HBM.
            # NOTE: single-member undivided results may ALIAS the input
            # tree (treat op results as immutable, the jax norm —
            # multi-member paths return fresh buffers).
            if divisor is not None and divisor != 1:
                import jax

                return jax.tree_util.tree_map(
                    lambda l: _divide_leaf(l, divisor)
                    if hasattr(l, "__truediv__")
                    else l,
                    tree,
                )
            return tree
        leaves, treedef = _flatten(tree)
        if not leaves:
            return tree
        if op == ReduceOp.AVG:
            divisor = self._world_size
        native_op = int(ReduceOp.SUM if op == ReduceOp.AVG else op)

        if all(_is_jax_array(l) for l in leaves):
            return self._allreduce_device_packed(
                leaves, treedef, native_op, divisor, timeout_ms
            )

        arrays = [_as_numpy(l) for l in leaves]
        was_jax = [_is_jax_array(l) for l in leaves]
        # Group leaves by accumulation dtype; pack each group into one
        # contiguous buffer so the ring runs once per dtype.
        out_arrays: List[Optional[np.ndarray]] = [None] * len(arrays)
        groups: dict = {}
        for i, a in enumerate(arrays):
            acc = a.dtype if a.dtype in _NATIVE_DTYPES else np.dtype(np.float32)
            groups.setdefault(acc, []).append(i)
        for acc_dtype, idxs in groups.items():
            buf = np.concatenate(
                [arrays[i].astype(acc_dtype, copy=False).ravel() for i in idxs]
            )
            _check(
                _lib.tft_hc_allreduce(
                    self._handle,
                    buf.ctypes.data_as(ctypes.c_void_p),
                    buf.size,
                    _NATIVE_DTYPES[acc_dtype],
                    native_op,
                    timeout_ms,
                )
            )
            if divisor is not None:
                if buf.dtype == _BF16:
                    buf = (buf.astype(np.float32) / divisor).astype(_BF16)
                elif np.issubdtype(buf.dtype, np.floating):
                    buf /= divisor
                else:
                    # int groups floor-divide by the integral divisor
                    # (the _divide_leaf contract); ``//= float`` would
                    # raise an unsafe-cast error in-place.
                    buf //= int(divisor)
            offset = 0
            for i in idxs:
                n = arrays[i].size
                out_arrays[i] = (
                    buf[offset : offset + n]
                    .reshape(arrays[i].shape)
                    .astype(arrays[i].dtype, copy=False)
                )
                offset += n
        out_leaves: List[Any] = []
        for i, a in enumerate(out_arrays):
            if was_jax[i]:
                import jax.numpy as jnp

                out_leaves.append(jnp.asarray(a))
            else:
                out_leaves.append(a)
        return _unflatten(treedef, out_leaves)

    def _allreduce_device_packed(
        self, leaves, treedef, native_op: int, divisor, timeout_ms: int
    ) -> Any:
        """All-jax-leaf fast path: pack on device, then pipeline the WHOLE
        op schedule — every dtype bucket's chunk DMAs are enqueued up
        front, so bucket i+1's d2h streams while bucket i rides the ring
        and bucket i-1's result re-uploads under jax's async dispatch. The
        old per-buffer pipeline drained between dtype groups; a mixed
        f32/bf16/int gradient tree paid a full pipeline fill+drain per
        group."""
        import jax.numpy as jnp

        key = (treedef, tuple((l.shape, np.dtype(l.dtype)) for l in leaves))
        packer = self._packers.get(key)
        if packer is None:
            packer = self._packers[key] = _DevicePacker(leaves)
        t_pack = time.perf_counter()
        bufs = packer.pack(leaves)
        names = sorted(bufs)  # deterministic bucket order = the op schedule

        # Chunk schedule across ALL buckets. Chunk boundaries depend only
        # on (size, pipeline config), both store-negotiated, so every rank
        # issues the identical sequence of native ring ops.
        schedule: List[Tuple[str, Any]] = []
        for name in names:
            dev = bufs[name]
            itemsize = np.dtype(dev.dtype).itemsize
            k = self._pipeline_chunks
            if k <= 1 or dev.size * itemsize < self._pipeline_min_bytes:
                schedule.append((name, dev))
            else:
                bounds = [dev.size * i // k for i in range(k + 1)]
                schedule.extend(
                    (name, dev[a:b]) for a, b in zip(bounds, bounds[1:])
                )
        for _, c in schedule:
            c.copy_to_host_async()  # queue every DMA before the first block
        pack_s = time.perf_counter() - t_pack

        out_chunks: dict = {name: [] for name in names}
        buckets: dict = {
            name: {"bytes": 0, "d2h": 0.0, "ring": 0.0, "h2d": 0.0,
                   "stripe_s": [], "stripe_wall": 0.0}
            for name in names
        }
        for name, c in schedule:
            st = buckets[name]
            t0 = time.perf_counter()
            arr = np.asarray(c)  # completes when THIS chunk's DMA lands
            if not arr.flags.writeable or not arr.flags.c_contiguous:
                arr = np.array(arr)  # ring reduces in place
            t1 = time.perf_counter()
            self._ring_chunk(arr, native_op, timeout_ms)
            stripe_s = self._last_stripe_seconds()
            if divisor is not None:
                arr = self._apply_divisor(arr, divisor)
            t2 = time.perf_counter()
            # Async dispatch: the upload starts now and overlaps the next
            # chunk's (possibly next bucket's) ring pass.
            out_chunks[name].append(jnp.asarray(arr))
            st["bytes"] += arr.nbytes
            st["d2h"] += t1 - t0
            st["ring"] += t2 - t1
            st["h2d"] += time.perf_counter() - t2
            # elementwise-sum the per-stripe ring seconds over the
            # bucket's chunks (chunks can use fewer effective stripes)
            acc = st["stripe_s"]
            for i, s in enumerate(stripe_s):
                if i < len(acc):
                    acc[i] += s
                else:
                    acc.append(s)
            # pure transport wall: the slowest stripe bounds each chunk's
            # ring pass; summing per-chunk maxima excludes the peer-skew
            # wait the `ring` phase absorbs at the op-header sync, so this
            # is the number a stripe-count sweep compares
            if stripe_s:
                st["stripe_wall"] += max(stripe_s)
        dev_bufs = {
            name: (chunks[0] if len(chunks) == 1 else jnp.concatenate(chunks))
            for name, chunks in out_chunks.items()
        }
        total_bytes = sum(b["bytes"] for b in buckets.values())
        self._record_op_stats({
            "op": "allreduce",
            "bytes": total_bytes,
            # native dtypes ride both legs at full width
            "d2h_bytes": total_bytes,
            "chunks": len(schedule),
            "pack": pack_s,
            "d2h": sum(b["d2h"] for b in buckets.values()),
            "ring": sum(b["ring"] for b in buckets.values()),
            "h2d": sum(b["h2d"] for b in buckets.values()),
            "buckets": buckets,
        })
        return _unflatten(treedef, packer.unpack(dev_bufs))

    def _apply_divisor(self, arr: np.ndarray, divisor) -> np.ndarray:
        if arr.dtype == _BF16:
            return (arr.astype(np.float32) / divisor).astype(_BF16)
        if np.issubdtype(arr.dtype, np.floating):
            arr /= divisor
            return arr
        arr //= int(divisor)
        return arr

    def _ring_chunk(self, arr: np.ndarray, native_op: int, timeout_ms: int) -> None:
        _check(
            _lib.tft_hc_allreduce(
                self._handle,
                arr.ctypes.data_as(ctypes.c_void_p),
                arr.size,
                _NATIVE_DTYPES[arr.dtype],
                native_op,
                timeout_ms,
            )
        )

    # -- two-tier (topology-aware) ops --

    def hier_capable(self) -> bool:
        """Whether the last configure() received a usable topology map —
        a region map with >= 2 distinct labels and/or a host map grouping
        >= 2 co-hosted ranks — and built the hierarchical topology
        alongside the flat ring."""
        return bool(_lib.tft_hc_hier_capable(self._handle))

    def host_tier_transport(self) -> str:
        """Transport of the host (intra-host) tier after the last
        configure: ``"shm"`` (shared-memory rings), ``"tcp"`` (the
        ``TORCHFT_HC_SHM=0`` loopback fallback) or ``"none"`` (this
        member's (region, host) group has < 2 ranks)."""
        code = int(_lib.tft_hc_host_tier_transport(self._handle))
        return {0: "none", 1: "tcp", 2: "shm"}[code]

    def _last_hier_dict(self) -> dict:
        out = ctypes.c_void_p()
        _check(_lib.tft_hc_last_hier_json(self._handle, ctypes.byref(out)))
        return json.loads(_native._take_string(out))

    @staticmethod
    def _hier_stats_fields(h: dict) -> dict:
        """The op-stat fragment shared by the bulk and plan hier paths:
        per-tier phase seconds + MEASURED per-tier tx bytes (duplex's
        per-connection counters, summed) — ONE schema, so consumers
        (bench accounting, diagnosis tooling) never see the two paths
        drift."""
        out = {
            # The wire bill: MEASURED socket traffic only. The shm host
            # tier hands nothing to the kernel, so its hops contribute 0
            # here by construction (host_tx_bytes is non-zero only under
            # the TORCHFT_HC_SHM=0 TCP fallback).
            "wire_bytes": h["intra_tx_bytes"] + h["inter_tx_bytes"]
            + h["host_tx_bytes"],
            "intra_rs_s": h["intra_rs_s"],
            "intra_ag_s": h["intra_ag_s"],
            "inter_ring_s": h["inter_ring_s"],
            "intra_bcast_s": h["intra_bcast_s"],
            "tiers": {
                "intra": {
                    "tx_bytes": h["intra_tx_bytes"],
                    "world": h["intra_world"],
                    "eff": h["eff_intra"],
                    "rs_s": h["intra_rs_s"],
                    "ag_s": h["intra_ag_s"],
                    "bcast_s": h["intra_bcast_s"],
                },
                "inter": {
                    "tx_bytes": h["inter_tx_bytes"],
                    "rs_tx_bytes": h["inter_rs_tx_bytes"],
                    "ag_tx_bytes": h["inter_ag_tx_bytes"],
                    "world": h["inter_world"],
                    "eff": h["eff_inter"],
                    "ring_s": h["inter_ring_s"],
                    "leader": h["leader"],
                },
            },
        }
        if h.get("host_world", 0) > 1:
            # The third (intra-host) tier, present only on co-hosted
            # cohorts: shm_* phase keys + the honest byte split (tx_bytes
            # = kernel traffic, 0 under shm; shm_bytes = ring movement).
            out["shm_rs_s"] = h["shm_rs_s"]
            out["shm_ag_s"] = h["shm_ag_s"]
            out["shm_bcast_s"] = h["shm_bcast_s"]
            out["tiers"]["host"] = {
                "tx_bytes": h["host_tx_bytes"],
                "shm_bytes": h["shm_bytes"],
                "world": h["host_world"],
                "eff": h["eff_host"],
                "rs_s": h["shm_rs_s"],
                "ag_s": h["shm_ag_s"],
                "bcast_s": h["shm_bcast_s"],
                "leader": h["host_leader"],
                "transport": "shm" if h["host_shm"] else "tcp",
            }
        return out

    @staticmethod
    def _merge_hier_stats(acc: Optional[dict], h: dict) -> dict:
        """Accumulates per-group native hier breakdowns (one native op per
        dtype group overwrites last_hier_) into one per-op record."""
        if acc is None:
            return dict(h)
        for k in (
            "intra_rs_s", "intra_ag_s", "inter_ring_s", "intra_bcast_s",
            "intra_tx_bytes", "inter_tx_bytes", "inter_rs_tx_bytes",
            "inter_ag_tx_bytes", "payload_bytes",
            "shm_rs_s", "shm_ag_s", "shm_bcast_s", "host_tx_bytes",
            "shm_bytes",
        ):
            acc[k] += h[k]
        return acc

    def allreduce_hier(
        self,
        tree: Any,
        op: ReduceOp = ReduceOp.SUM,
        divisor: Optional[float] = None,
        wire: Optional[str] = None,
    ) -> Work:
        """Two-tier allreduce (see Collectives.allreduce_hier): intra-
        region reduce-scatter -> intra allgather -> striped inter-region
        ring among one deterministic leader per region (lowest
        replica-id) -> chunk-pipelined intra broadcast, composed from the
        SAME native rs/ag stripe bodies as the flat ring. ``wire``
        applies to the inter hop only (``"bf16"`` halves its bytes,
        ``"q8"`` quarters them with per-chunk scales) — quantization
        noise is paid once per sync, on the slow link. Requires a
        hier-capable configure; raises otherwise (the managed dispatch
        latches it — the probe candidates' sentinel discipline)."""
        timeout_ms = _ms(self._timeout)
        if wire not in _HIER_WIRES:
            raise ValueError(f"unsupported hier wire: {wire!r}")
        if op == ReduceOp.AVG:
            if divisor is not None:
                raise ValueError("divisor only composes with ReduceOp.SUM")
            divisor, op = float(self._world_size), ReduceOp.SUM
        if divisor is not None and op != ReduceOp.SUM:
            raise ValueError("divisor only composes with ReduceOp.SUM")
        if wire is not None and op != ReduceOp.SUM:
            raise ValueError("hier wire bf16/q8 supports SUM/AVG only")
        return self._submit(
            lambda: self._allreduce_hier_sync(tree, op, divisor, wire,
                                              timeout_ms)
        )

    def _allreduce_hier_sync(
        self,
        tree: Any,
        op: ReduceOp,
        divisor: Optional[float],
        wire: Optional[str],
        timeout_ms: int,
    ) -> Any:
        if self._world_size == 1:
            if divisor is not None and divisor != 1:
                import jax

                return jax.tree_util.tree_map(
                    lambda l: _divide_leaf(l, divisor)
                    if hasattr(l, "__truediv__")
                    else l,
                    tree,
                )
            return tree
        leaves, treedef = _flatten(tree)
        if not leaves:
            return tree
        native_op = int(op)
        all_jax = all(_is_jax_array(l) for l in leaves)
        f32 = np.dtype(np.float32)

        t0 = time.perf_counter()
        if all_jax:
            key = (
                "hier_q8" if wire == "q8" else "hier", treedef,
                tuple((l.shape, np.dtype(l.dtype)) for l in leaves),
            )
            packer = self._packers.get(key)
            if packer is None:
                packer = self._packers[key] = _DevicePacker(
                    leaves, force_f32=(wire == "q8")
                )
            bufs = packer.pack(leaves)
            names = sorted(bufs)
            for name in names:  # queue every DMA before blocking on one
                bufs[name].copy_to_host_async()
            host = {}
            for name in names:
                arr = np.asarray(bufs[name])
                if not arr.flags.writeable or not arr.flags.c_contiguous:
                    arr = np.array(arr)  # the schedule reduces in place
                host[name] = arr
            arrays = was_jax = None
        else:
            packer = None
            arrays = [_as_numpy(l) for l in leaves]
            was_jax = [_is_jax_array(l) for l in leaves]
            groups: dict = {}
            for i, a in enumerate(arrays):
                if wire == "q8":
                    acc = f32  # the quantized inter hop reduces ONE f32 group
                else:
                    acc = (a.dtype if a.dtype in _NATIVE_DTYPES else f32)
                groups.setdefault(str(acc), []).append(i)
            host = {
                name: np.concatenate(
                    [arrays[i].astype(np.dtype(name), copy=False).ravel()
                     for i in idxs]
                )
                for name, idxs in groups.items()
            }
            names = sorted(host)
        d2h_s = time.perf_counter() - t0

        t1 = time.perf_counter()
        hier_stats: Optional[dict] = None
        for name in names:
            buf = host[name]
            # The wire applies where it means something: the q8 grouping
            # is a single f32 buffer by construction, and bf16 compresses
            # f32 groups only (others ride the inter hop at native width).
            if wire == "q8":
                gw = _HIER_WIRES["q8"]
            elif wire == "bf16" and buf.dtype == f32:
                gw = _HIER_WIRES["bf16"]
            else:
                gw = _HIER_WIRES[None]
            _check(
                _lib.tft_hc_allreduce_hier(
                    self._handle,
                    buf.ctypes.data_as(ctypes.c_void_p),
                    buf.size,
                    _NATIVE_DTYPES[buf.dtype],
                    native_op,
                    gw,
                    timeout_ms,
                )
            )
            hier_stats = self._merge_hier_stats(
                hier_stats, self._last_hier_dict()
            )
            if divisor is not None and divisor != 1:
                host[name] = self._apply_divisor(buf, divisor)
        ring_s = time.perf_counter() - t1

        t2 = time.perf_counter()
        if all_jax:
            import jax.numpy as jnp

            out = _unflatten(
                treedef,
                packer.unpack(
                    {name: jnp.asarray(host[name]) for name in names}
                ),
            )
        else:
            out_leaves: List[Any] = [None] * len(arrays)
            for name, idxs in groups.items():
                buf = host[name]
                offset = 0
                for i in idxs:
                    n = arrays[i].size
                    leaf = (
                        buf[offset:offset + n]
                        .reshape(arrays[i].shape)
                        .astype(arrays[i].dtype, copy=False)
                    )
                    offset += n
                    if was_jax[i]:
                        import jax.numpy as jnp

                        leaf = jnp.asarray(leaf)
                    out_leaves[i] = leaf
            out = _unflatten(treedef, out_leaves)
        total_bytes = sum(host[n].nbytes for n in names)
        st: dict = {
            "op": "allreduce_hier",
            "wire": wire,
            "bytes": total_bytes,
            "d2h_bytes": total_bytes if all_jax else 0,
            # MEASURED traffic this member sent, per tier (duplex's
            # per-connection counters, summed) — the number that shows
            # the inter-tier byte reduction directly, not a model.
            "d2h": d2h_s,
            "ring": ring_s,
            "h2d": time.perf_counter() - t2,
        }
        if hier_stats is not None:
            st.update(self._hier_stats_fields(hier_stats))
        self._record_op_stats(st)
        return out

    # -- planned ops --

    def plan_allreduce(
        self,
        tree: Any,
        op: ReduceOp = ReduceOp.SUM,
        divisor: Optional[float] = None,
        wire: Optional[str] = None,
        device_pack: Optional[bool] = None,
        hier: bool = False,
    ) -> Work:
        """The plan-path allreduce (see Collectives.plan_allreduce): one
        native call per step over a cached, precompiled plan. Bit-identical
        to the legacy managed path — the plan executes the identical
        per-group stripe partition through the same native ring bodies.
        Unsupported signatures (non-native leaf dtypes; q8 wires with
        non-float leaves) silently take the legacy path with equivalent
        semantics where one exists (``wire=None``), else raise.

        ``hier`` executes the plan over the TWO-TIER schedule (requires a
        hier-capable configure; the error latches under the managed
        discipline otherwise). The wire applies at the leader's
        inter-region hop only; ``device_pack`` is ignored on this path —
        there is no pre-packed hier form, because the wire encoding
        happens at the inter boundary, not at pack.

        ``device_pack``: ``True`` packs the wire encoding ON DEVICE
        (Pallas quantize/cast kernels + prepacked plan leaves) so the
        device->host transfer costs wire bytes, not f32 bytes — supported
        for wires ``None``/``"bf16"``/``"q8ef"`` on all-jax trees, with a
        silent host-pack fallback where the capability is missing (CPU
        rings without the kernels, non-jax leaves, plain ``"q8"``).
        ``False`` pins host pack. ``None`` (default) resolves
        ``TORCHFT_DEVICE_PACK``: ``on``/``off`` pin, ``auto`` (the
        default) device-packs only where a real device link exists (the
        TPU backend). Results are bit-identical either way — device- and
        host-packing members may share one ring."""
        timeout_ms = _ms(self._timeout)
        if wire not in _PLAN_WIRES:
            raise ValueError(f"unsupported wire: {wire!r}")
        if op == ReduceOp.AVG:
            if divisor is not None:
                # Mirror the legacy path's loud error — silently
                # replacing a caller's participant divisor with
                # world_size would corrupt the average whenever
                # participants < world.
                raise ValueError("divisor only composes with ReduceOp.SUM")
            divisor, op = float(self._world_size), ReduceOp.SUM
        if op != ReduceOp.SUM:
            raise ValueError("plan_allreduce supports SUM/AVG only")
        # Parse the knob EAGERLY (static usage errors raise here, before
        # the submit, matching the wire/op validation above — an op-thread
        # ValueError would be latched by Manager's dispatch and silently
        # discard every step instead).
        device_pack = _resolve_device_pack_setting(device_pack)
        return self._submit(
            lambda: self._plan_allreduce_sync(
                tree, divisor, wire, timeout_ms, device_pack, hier
            )
        )

    def _resolve_device_pack(
        self, setting: Optional[bool], leaves: Sequence[Any],
        wire: Optional[str],
    ) -> bool:
        """Whether this sync should ATTEMPT the device pack (a failed
        packer build still falls back to host pack — the verdict caches).
        ``setting`` is the already-parsed knob (True/False/None = auto);
        auto engages only where the pack saves a real device-link leg."""
        if setting is False:
            return False
        if wire not in _DEVICE_PACK_WIRES:
            return False
        if not leaves or not all(_is_jax_array(l) for l in leaves):
            return False
        if setting is True:
            return True
        import jax

        return jax.default_backend() == "tpu"

    def _device_packer_for(
        self, leaves: Sequence[Any], treedef: Any, wire: Optional[str]
    ) -> Optional[_DeviceWirePacker]:
        sig = tuple((l.shape, np.dtype(l.dtype)) for l in leaves)
        key = (wire, treedef, sig)
        if key in self._dev_packers:
            return self._dev_packers[key]
        try:
            packer: Optional[_DeviceWirePacker] = _DeviceWirePacker(
                leaves, wire
            )
        except Exception:  # noqa: BLE001 - unsupported signature, or the
            # Pallas kernels are unavailable on this install: cache the
            # verdict, host pack serves the identical contract.
            packer = None
        self._dev_packers[key] = packer
        return packer

    def _plan_for(
        self, leaves: Sequence[Any], treedef: Any, wire: Optional[str],
        prepacked: bool = False, hier: bool = False,
    ) -> Optional[_CommPlan]:
        # The signature MUST stay in the key: executing a plan against a
        # same-treedef tree with different shapes/dtypes would pack with
        # the wrong per-leaf counts (reading past leaf buffers). It is
        # computed once here and handed to the plan, never recomputed.
        sig = tuple((l.shape, np.dtype(l.dtype)) for l in leaves)
        key: Any = (wire, treedef, sig)
        if prepacked:
            key = (wire, treedef, sig, "pre")
        elif hier:
            key = (wire, treedef, sig, "hier")
        if key in self._plans:
            return self._plans[key]
        try:
            plan: Optional[_CommPlan] = _CommPlan(
                self._handle, sig, treedef, wire,
                stripes=self._stripes, world=self._world_size,
                prepacked=prepacked, hier=hier,
            )
        except (KeyError, RuntimeError):
            # Non-native leaf dtype, or a wire/dtype combination the
            # native plan rejects: remember the verdict so the per-step
            # path doesn't re-attempt the build.
            plan = None
        self._plans[key] = plan
        return plan

    def _plan_allreduce_sync(
        self,
        tree: Any,
        divisor: Optional[float],
        wire: Optional[str],
        timeout_ms: int,
        device_pack: Optional[bool] = None,
        hier: bool = False,
    ) -> Any:
        leaves, treedef = _flatten(tree)
        if not leaves:
            return tree
        if hier:
            return self._plan_hier_sync(
                leaves, treedef, tree, divisor, wire, timeout_ms
            )
        if self._resolve_device_pack(device_pack, leaves, wire):
            packer = self._device_packer_for(leaves, treedef, wire)
            plan = (
                self._plan_for(leaves, treedef, wire, prepacked=True)
                if packer is not None else None
            )
            if packer is not None and plan is not None:
                return self._plan_execute_device(
                    plan, packer, leaves, treedef, divisor, wire, timeout_ms
                )
            # capability shortfall (kernels unavailable / unsupported
            # signature): host pack serves the identical contract
        plan = self._plan_for(leaves, treedef, wire)
        if plan is None:
            if wire is None:
                return self._allreduce_sync(
                    tree, ReduceOp.SUM, timeout_ms, divisor
                )
            if wire in ("q8", "q8ef"):
                raise ValueError(
                    "plan wire 'q8'/'q8ef' requires f32/bf16 leaves"
                )
            raise ValueError(
                "plan wire 'bf16' requires native-dtype leaves"
            )
        t0 = time.perf_counter()
        staging_allocs = 0
        refs = []  # keep host views alive across the native call
        in_ptrs = plan.in_ptrs
        for i, l in enumerate(leaves):
            a = np.asarray(l)  # zero-copy for numpy / CPU jax leaves
            if not a.flags.c_contiguous:
                a = np.ascontiguousarray(a)
                staging_allocs += 1
            refs.append(a)
            in_ptrs[i] = a.ctypes.data
        t1 = time.perf_counter()
        outs = plan.out_sets[plan.flip]
        out_ptrs = plan.out_ptrs[plan.flip]
        plan.flip ^= 1
        _check(
            _lib.tft_plan_execute(
                self._handle,
                plan.plan_id,
                in_ptrs,
                out_ptrs,
                float(divisor if divisor is not None else 1.0),
                0 if divisor is None else 1,
                timeout_ms,
            )
        )
        ring_s = time.perf_counter() - t1
        del refs
        plan.execs += 1
        self._record_op_stats({
            "op": "plan_allreduce",
            "wire": wire,
            "device_pack": False,
            "bytes": plan.bytes,
            "wire_bytes": plan.wire_bytes,
            # Host pack reads every leaf at full source width: the device
            # link pays f32-size bytes regardless of the wire encoding.
            "d2h_bytes": plan.bytes,
            "d2h": t1 - t0,  # pointer gather; host leaves make it ~free
            "ring": ring_s,  # the single native call: pack+ring+unpack
            # Per-bucket phases, fetched raw here and decoded lazily at
            # pop_op_stats: the JSON parse stays off the per-step path.
            "_buckets_json": self._plan_stats_json(plan.plan_id),
            # The zero-allocation contract: after warmup, no Python-side
            # staging buffer is allocated on this path (only forced
            # copies of non-contiguous inputs would count here).
            "py_staging_allocs": staging_allocs,
            "plan_execs": plan.execs,
        })
        return _unflatten(treedef, outs)

    def _plan_hier_sync(
        self,
        leaves: Sequence[Any],
        treedef: Any,
        tree: Any,
        divisor: Optional[float],
        wire: Optional[str],
        timeout_ms: int,
    ) -> Any:
        """Hier plan execute: ONE native call runs the whole two-tier
        schedule per group (pack streamed into the intra reduce-scatter,
        unpack out of the broadcast — the triple pipeline survives the
        extra tiers), with the wire applied at the leader's inter hop."""
        if self._world_size > 1 and not self.hier_capable():
            raise RuntimeError(
                "plan_allreduce(hier=True) needs a hier-capable configure: "
                "the quorum's region map had < 2 distinct labels (or "
                "unlabeled members) — single-region cohorts ride the flat "
                "plan"
            )
        plan = self._plan_for(leaves, treedef, wire, hier=True)
        if plan is None:
            if wire is None:
                # Non-native leaf dtypes: the bulk hier path groups them
                # into f32 with equivalent semantics.
                return self._allreduce_hier_sync(
                    tree, ReduceOp.SUM, divisor, None, timeout_ms
                )
            if wire in ("q8", "q8ef"):
                raise ValueError(
                    "hier plan wire 'q8'/'q8ef' requires f32/bf16 leaves"
                )
            raise ValueError(
                "hier plan wire 'bf16' requires native-dtype leaves"
            )
        t0 = time.perf_counter()
        staging_allocs = 0
        refs = []  # keep host views alive across the native call
        in_ptrs = plan.in_ptrs
        for i, l in enumerate(leaves):
            a = np.asarray(l)  # zero-copy for numpy / CPU jax leaves
            if not a.flags.c_contiguous:
                a = np.ascontiguousarray(a)
                staging_allocs += 1
            refs.append(a)
            in_ptrs[i] = a.ctypes.data
        t1 = time.perf_counter()
        outs = plan.out_sets[plan.flip]
        out_ptrs = plan.out_ptrs[plan.flip]
        plan.flip ^= 1
        _check(
            _lib.tft_plan_execute(
                self._handle,
                plan.plan_id,
                in_ptrs,
                out_ptrs,
                float(divisor if divisor is not None else 1.0),
                0 if divisor is None else 1,
                timeout_ms,
            )
        )
        ring_s = time.perf_counter() - t1
        del refs
        plan.execs += 1
        st: dict = {
            "op": "plan_allreduce",
            "wire": wire,
            "hier": True,
            "device_pack": False,
            "bytes": plan.bytes,
            "d2h_bytes": plan.bytes,
            "d2h": t1 - t0,  # pointer gather; host leaves make it ~free
            "ring": ring_s,  # the single native call: the whole schedule
            "_buckets_json": self._plan_stats_json(plan.plan_id),
            "py_staging_allocs": staging_allocs,
            "plan_execs": plan.execs,
        }
        if self._world_size > 1:
            st.update(self._hier_stats_fields(self._last_hier_dict()))
        else:
            st["wire_bytes"] = plan.wire_bytes
        self._record_op_stats(st)
        return _unflatten(treedef, outs)

    def _plan_execute_device(
        self,
        plan: _CommPlan,
        packer: _DeviceWirePacker,
        leaves: Sequence[Any],
        treedef: Any,
        divisor: Optional[float],
        wire: Optional[str],
        timeout_ms: int,
    ) -> Any:
        """Device-packed plan execute: the Pallas kernels emit the wire
        encoding on the accelerator (advancing the device-resident EF
        carry on the q8ef wire), only WIRE-sized bytes cross d2h, and the
        prepacked native plan decodes them straight into its staging —
        ring and unpack are the host-pack plan's own, so results are
        bit-identical to host packing."""
        t0 = time.perf_counter()
        payloads, scales = packer.pack_step(leaves)
        for a in payloads:
            a.copy_to_host_async()
        for a in scales:
            a.copy_to_host_async()
        t1 = time.perf_counter()
        staging_allocs = 0
        host_payloads: List[np.ndarray] = []
        for a in payloads:
            h = np.asarray(a)
            if not h.flags.c_contiguous:
                h = np.ascontiguousarray(h)
                staging_allocs += 1
            host_payloads.append(h)
        host_scales = [
            np.ascontiguousarray(np.asarray(a)) for a in scales
        ]
        t2 = time.perf_counter()
        gin, gaux = plan.group_in, plan.group_aux
        q8 = wire in ("q8", "q8ef")
        for gi, h in enumerate(host_payloads):
            gin[gi] = h.ctypes.data
            gaux[gi] = host_scales[gi].ctypes.data if q8 else None
        outs = plan.out_sets[plan.flip]
        out_ptrs = plan.out_ptrs[plan.flip]
        plan.flip ^= 1
        _check(
            _lib.tft_plan_execute_pre(
                self._handle,
                plan.plan_id,
                gin,
                gaux,
                out_ptrs,
                float(divisor if divisor is not None else 1.0),
                0 if divisor is None else 1,
                timeout_ms,
            )
        )
        ring_s = time.perf_counter() - t2
        plan.execs += 1
        d2h_bytes = sum(h.nbytes for h in host_payloads) + sum(
            h.nbytes for h in host_scales
        )
        self._record_op_stats({
            "op": "plan_allreduce",
            "wire": wire,
            "device_pack": True,
            "bytes": plan.bytes,
            "wire_bytes": plan.wire_bytes,
            # The tentpole number: the device link carried the WIRE
            # encoding (int8 codes + scale sidecar / bf16 words), not the
            # full-width leaves.
            "d2h_bytes": d2h_bytes,
            "pack": t1 - t0,   # device kernel dispatch + DMA enqueue
            "d2h": t2 - t1,    # blocking readback of the wire buffers
            "ring": ring_s,    # the single native call: decode+ring+unpack
            "_buckets_json": self._plan_stats_json(plan.plan_id),
            "py_staging_allocs": staging_allocs,
            "plan_execs": plan.execs,
        })
        return _unflatten(treedef, outs)

    def _plan_stats_json(self, plan_id: int) -> str:
        out = ctypes.c_void_p()
        _check(_lib.tft_plan_stats_json(self._handle, plan_id, ctypes.byref(out)))
        return _native._take_string(out)

    def plan_reset_feedback(self) -> None:
        """Zeroes the EF carry of every cached q8ef plan — native AND
        device-resident (the device packer owns the carry on the
        device-pack path) — the heal/abort discipline. Runs on the op
        thread so it cannot interleave with an in-flight execute."""
        def reset() -> None:
            for plan in self._plans.values():
                if plan is not None and plan.wire == "q8ef":
                    _check(
                        _lib.tft_plan_reset_feedback(
                            self._handle, plan.plan_id
                        )
                    )
            for packer in self._dev_packers.values():
                if packer is not None:
                    packer.reset_feedback()
        self._submit(reset).wait()

    def allgather(self, tree: Any) -> Work:
        timeout_ms = _ms(self._timeout)
        return self._submit(lambda: self._allgather_sync(tree, timeout_ms))

    def _allgather_sync(self, tree: Any, timeout_ms: int) -> List[Any]:
        if self._world_size == 1:
            return [tree]
        leaves, treedef = _flatten(tree)
        if leaves and all(_is_jax_array(l) for l in leaves):
            # Device-packed fast path, mirroring allreduce's: without it,
            # a quantized {q, scale} payload of ~60 leaves costs ~60
            # device->host round-trips — measured 3.5 s/step on the
            # tunneled TPU (~100 ms RTT each) vs ~0.25 s of actual
            # bandwidth for the same bytes.
            return self._allgather_device_packed(leaves, treedef, timeout_ms)
        arrays = [np.ascontiguousarray(_as_numpy(l)) for l in leaves]
        was_jax = [_is_jax_array(l) for l in leaves]
        packed = b"".join(a.tobytes() for a in arrays)
        nbytes = len(packed)
        inbuf = ctypes.create_string_buffer(packed, nbytes) if nbytes else None
        out = np.empty(max(nbytes * self._world_size, 1), dtype=np.uint8)
        _check(
            _lib.tft_hc_allgather(
                self._handle,
                inbuf,
                out.ctypes.data_as(ctypes.c_void_p),
                nbytes,
                timeout_ms,
            )
        )
        results: List[Any] = []
        for r in range(self._world_size):
            offset = r * nbytes
            out_leaves: List[Any] = []
            for i, a in enumerate(arrays):
                leaf = (
                    out[offset : offset + a.nbytes]
                    .view(a.dtype)
                    .reshape(a.shape)
                    .copy()
                )
                offset += a.nbytes
                if was_jax[i]:
                    import jax.numpy as jnp

                    leaf = jnp.asarray(leaf)
                out_leaves.append(leaf)
            results.append(_unflatten(treedef, out_leaves))
        return results

    def _allgather_device_packed(
        self, leaves, treedef, timeout_ms: int
    ) -> List[Any]:
        """All-jax-leaf allgather: one jitted on-device concat per EXACT
        dtype (byte-preserving — no accumulation upcasts), one d2h per
        dtype group, one ring gather over the concatenated groups, then
        per-member on-device unpack."""
        import jax.numpy as jnp

        key = (
            "ag", treedef,
            tuple((l.shape, np.dtype(l.dtype)) for l in leaves),
        )
        packer = self._packers.get(key)
        if packer is None:
            packer = self._packers[key] = _DevicePacker(
                leaves, exact_dtypes=True
            )
        t0 = time.perf_counter()
        bufs = packer.pack(leaves)
        names = sorted(bufs)  # deterministic group order on the wire
        for name in names:  # queue every DMA before blocking on the first
            bufs[name].copy_to_host_async()
        t1 = time.perf_counter()
        host = {name: np.ascontiguousarray(np.asarray(bufs[name]))
                for name in names}
        t2 = time.perf_counter()
        packed = b"".join(host[name].tobytes() for name in names)
        nbytes = len(packed)
        inbuf = ctypes.create_string_buffer(packed, nbytes) if nbytes else None
        out = np.empty(max(nbytes * self._world_size, 1), dtype=np.uint8)
        t2b = time.perf_counter()  # host staging copies are not the wire
        _check(
            _lib.tft_hc_allgather(
                self._handle,
                inbuf,
                out.ctypes.data_as(ctypes.c_void_p),
                nbytes,
                timeout_ms,
            )
        )
        t3 = time.perf_counter()
        stripe_s = self._last_stripe_seconds()
        results: List[Any] = []
        for r in range(self._world_size):
            offset = r * nbytes
            member_bufs = {}
            for name in names:
                a = host[name]
                member_bufs[name] = jnp.asarray(
                    out[offset : offset + a.nbytes].view(a.dtype)
                )
                offset += a.nbytes
            results.append(_unflatten(treedef, packer.unpack(member_bufs)))
        self._record_op_stats({
            "op": "allgather", "bytes": nbytes,
            # this rank's packed groups cross down once; the gathered
            # members come back on the h2d leg
            "d2h_bytes": nbytes,
            "pack": t1 - t0, "d2h": t2 - t1, "host_copy": t2b - t2,
            "ring": t3 - t2b, "h2d": time.perf_counter() - t3,
            "stripe_s": stripe_s,
        })
        return results

    # -- sharded (split) ops --

    def _shard_ranges(
        self, count: int, esize: int, eff: int
    ) -> List[Tuple[int, int]]:
        """(start, len) element ranges this rank owns of a count-element
        group at the pinned stripe partition (native layout arithmetic)."""
        if self._world_size == 1:
            return [(0, count)]
        buf = (ctypes.c_int64 * (2 * _MAX_STRIPES))()
        n = _lib.tft_hc_shard_ranges(
            self._handle, count, esize, self._rank, eff, buf, _MAX_STRIPES
        )
        if n < 0:
            _check(2)
        return [(buf[2 * i], buf[2 * i + 1]) for i in range(n)]

    def reduce_scatter(
        self,
        tree: Any,
        op: ReduceOp = ReduceOp.SUM,
        divisor: Optional[float] = None,
        wire: Optional[str] = None,
        grid_shard: bool = False,
    ) -> Work:
        """``grid_shard`` (q8 wire only) applies the fused op's phase-2
        owner quantize+decode to the owned shard, so reduce_scatter +
        allgather_into reproduces ``allreduce(wire='q8')`` bit-for-bit —
        the determinism oracle for decomposed-vs-fused tests. Production
        callers leave it False: the shard never rides the lossy phase-2
        wire, so it keeps full f32 precision for free."""
        timeout_ms = _ms(self._timeout)
        if wire not in (None, "q8"):
            raise ValueError(f"unsupported wire: {wire!r}")
        if grid_shard and wire != "q8":
            raise ValueError("grid_shard only applies to wire='q8'")
        if op == ReduceOp.AVG:
            divisor, op = float(self._world_size), ReduceOp.SUM
        if op != ReduceOp.SUM and (divisor is not None or wire == "q8"):
            raise ValueError(
                "divisor / wire='q8' compose with ReduceOp.SUM/AVG only"
            )
        return self._submit(
            lambda: self._reduce_scatter_sync(tree, op, divisor, wire,
                                              grid_shard, timeout_ms)
        )

    def _reduce_scatter_sync(
        self,
        tree: Any,
        op: ReduceOp,
        divisor: Optional[float],
        wire: Optional[str],
        grid_shard: bool,
        timeout_ms: int,
    ) -> TreeShard:
        """Phase 1 of the ring only: the full tree crosses d2h ONCE, the
        ring reduces it in place, and only the ~1/world_size owned shard
        re-uploads — the return leg and everything downstream of it scale
        with the shard, not the model."""
        leaves, treedef = _flatten(tree)
        if not leaves:
            raise ValueError("reduce_scatter of an empty tree")
        sig = tuple((l.shape, np.dtype(l.dtype)) for l in leaves)
        all_jax = all(_is_jax_array(l) for l in leaves)
        native_op = int(op)

        t0 = time.perf_counter()
        if all_jax:
            key = ("rsq8" if wire == "q8" else "rs", treedef, sig)
            packer = self._packers.get(key)
            if packer is None:
                packer = self._packers[key] = _DevicePacker(
                    leaves, force_f32=(wire == "q8")
                )
            bufs = packer.pack(leaves)
            names = sorted(bufs)
            for name in names:  # queue every DMA before blocking on one
                bufs[name].copy_to_host_async()
            host = {}
            for name in names:
                arr = np.asarray(bufs[name])
                if not arr.flags.writeable or not arr.flags.c_contiguous:
                    arr = np.array(arr)  # ring reduces in place
                host[name] = arr
            groups = {str(acc): idxs for acc, idxs in packer.groups.items()}
            was_jax = None
        else:
            packer = None
            arrays = [_as_numpy(l) for l in leaves]
            was_jax = [_is_jax_array(l) for l in leaves]
            groups = {}
            for i, a in enumerate(arrays):
                if wire == "q8":
                    acc = np.dtype(np.float32)
                else:
                    acc = (a.dtype if a.dtype in _NATIVE_DTYPES
                           else np.dtype(np.float32))
                groups.setdefault(str(acc), []).append(i)
            host = {
                name: np.concatenate(
                    [arrays[i].astype(np.dtype(name), copy=False).ravel()
                     for i in idxs]
                )
                for name, idxs in groups.items()
            }
            names = sorted(host)
        d2h_s = time.perf_counter() - t0

        t1 = time.perf_counter()
        values: Dict[str, Any] = {}
        counts: Dict[str, int] = {}
        ranges: Dict[str, List[Tuple[int, int]]] = {}
        layout: Dict[str, int] = {}
        dtypes: Dict[str, Any] = {}
        stripe_s: List[float] = []
        for name in names:
            buf = host[name]
            count = buf.size
            esize = 1 if wire == "q8" else buf.itemsize
            eff = _effective_stripes(count * esize, self._stripes)
            counts[name] = count
            layout[name] = eff
            dtypes[name] = buf.dtype
            rng = self._shard_ranges(count, esize, eff)
            ranges[name] = rng
            shard = np.empty(sum(l for _, l in rng), dtype=buf.dtype)
            if self._world_size == 1:
                shard[:] = buf
            elif wire == "q8":
                _check(
                    _lib.tft_hc_reduce_scatter_q8(
                        self._handle,
                        buf.ctypes.data_as(ctypes.c_void_p),
                        count,
                        shard.ctypes.data_as(ctypes.c_void_p),
                        1 if grid_shard else 0,
                        eff,
                        timeout_ms,
                    )
                )
            else:
                _check(
                    _lib.tft_hc_reduce_scatter(
                        self._handle,
                        buf.ctypes.data_as(ctypes.c_void_p),
                        count,
                        _NATIVE_DTYPES[buf.dtype],
                        native_op,
                        shard.ctypes.data_as(ctypes.c_void_p),
                        eff,
                        timeout_ms,
                    )
                )
            if self._world_size > 1:
                stripe_s.extend(self._last_stripe_seconds())
            if divisor is not None and divisor != 1:
                shard = self._apply_divisor(shard, divisor)
            values[name] = shard
        ring_s = time.perf_counter() - t1

        t2 = time.perf_counter()
        if all_jax:
            import jax.numpy as jnp

            values = {name: jnp.asarray(v) for name, v in values.items()}
        self._record_op_stats({
            "op": "reduce_scatter",
            "bytes": sum(host[n].nbytes for n in names),
            "shard_bytes": sum(
                np.asarray(v).nbytes for v in values.values()
            ),
            # q8 counts its scale sidecar (reduce-scatter runs ONE
            # quantized phase) + the op header, like every q8 path
            "wire_bytes": sum(
                counts[n] + _q8_wire_overhead(
                    layout[n], self._world_size, phases=1
                ) if wire == "q8" else counts[n] * host[n].itemsize
                for n in names
            ),
            # the full tree crosses down once (when it started on
            # device); only the shard returns
            "d2h_bytes": (
                sum(host[n].nbytes for n in names) if all_jax else 0
            ),
            "d2h": d2h_s, "ring": ring_s,
            "h2d": time.perf_counter() - t2,
            "stripe_s": stripe_s,
        })
        return TreeShard(
            values=values, counts=counts, ranges=ranges, layout=layout,
            dtypes=dtypes, groups=groups, treedef=treedef, sig=sig,
            rank=self._rank, world_size=self._world_size, packer=packer,
            was_jax=was_jax,
        )

    def allgather_into(
        self, shard: TreeShard, wire: Optional[str] = None
    ) -> Work:
        timeout_ms = _ms(self._timeout)
        if wire not in (None, "bf16"):
            raise ValueError(f"unsupported wire: {wire!r}")
        return self._submit(
            lambda: self._allgather_into_sync(shard, wire, timeout_ms)
        )

    def _allgather_into_sync(
        self, shard: TreeShard, wire: Optional[str], timeout_ms: int
    ) -> Any:
        """Phase 2 of the ring on CURRENT shard values: each member ships
        its (updated) shard, every member ends with the identical full
        tree. ``wire="bf16"`` rounds f32 groups to bfloat16 on the wire —
        half the bytes; every member (including the owner) adopts the
        decoded bf16 words, so the gathered tree is still bit-identical
        across ranks."""
        t0 = time.perf_counter()
        out_bufs: Dict[str, np.ndarray] = {}
        stripe_s: List[float] = []
        wire_bytes = 0
        d2h_bytes = 0
        for name in sorted(shard.counts):
            count = shard.counts[name]
            gdtype = np.dtype(shard.dtypes[name])
            eff = shard.layout[name]
            if _is_jax_array(shard.values[name]):
                d2h_bytes += np.asarray(shard.values[name]).nbytes
            vals = np.ascontiguousarray(np.asarray(shard.values[name]))
            if vals.dtype != gdtype:
                vals = vals.astype(gdtype)
            expected = sum(l for _, l in shard.ranges[name])
            if vals.size != expected:
                raise ValueError(
                    f"shard group {name!r} has {vals.size} elements, layout "
                    f"expects {expected} — pass the TreeShard from "
                    "reduce_scatter (values replaced, layout intact)"
                )
            wdtype = gdtype
            if wire == "bf16":
                if gdtype == np.dtype(np.float32):
                    wdtype = _BF16
                elif gdtype != _BF16:
                    raise ValueError(
                        "wire='bf16' applies to f32/bf16 groups only"
                    )
            wvals = np.ascontiguousarray(vals.astype(wdtype, copy=False))
            full = np.empty(count, dtype=wdtype)
            if self._world_size == 1:
                full[:] = wvals
            else:
                _check(
                    _lib.tft_hc_allgather_into(
                        self._handle,
                        wvals.ctypes.data_as(ctypes.c_void_p),
                        full.ctypes.data_as(ctypes.c_void_p),
                        count,
                        _NATIVE_DTYPES[np.dtype(wdtype)],
                        eff,
                        timeout_ms,
                    )
                )
                stripe_s.extend(self._last_stripe_seconds())
            wire_bytes += count * np.dtype(wdtype).itemsize
            if np.dtype(wdtype) != gdtype:
                full = full.astype(gdtype)
            out_bufs[name] = full
        ring_s = time.perf_counter() - t0

        t1 = time.perf_counter()
        if shard.packer is not None:
            import jax.numpy as jnp

            dev = {name: jnp.asarray(b) for name, b in out_bufs.items()}
            out = _unflatten(shard.treedef, shard.packer.unpack(dev))
        else:
            out_leaves: List[Any] = [None] * len(shard.sig)
            for name, idxs in shard.groups.items():
                buf = out_bufs[name]
                off = 0
                for i in idxs:
                    shape, dt = shard.sig[i]
                    n = int(np.prod(shape)) if shape else 1
                    leaf = buf[off:off + n].reshape(shape).astype(
                        dt, copy=False
                    )
                    off += n
                    if shard.was_jax is not None and shard.was_jax[i]:
                        import jax.numpy as jnp

                        leaf = jnp.asarray(leaf)
                    out_leaves[i] = leaf
            out = _unflatten(shard.treedef, out_leaves)
        self._record_op_stats({
            "op": "allgather_into",
            "bytes": sum(b.nbytes for b in out_bufs.values()),
            "wire_bytes": wire_bytes,
            # only this rank's (updated) shard crosses down; the full
            # gathered tree returns on the h2d leg
            "d2h_bytes": d2h_bytes,
            "ring": ring_s,
            "h2d": time.perf_counter() - t1,
            "stripe_s": stripe_s,
        })
        return out

    def plan_reduce_scatter(
        self,
        tree: Any,
        op: ReduceOp = ReduceOp.SUM,
        divisor: Optional[float] = None,
        wire: Optional[str] = None,
        ag_wire: Optional[str] = None,
    ) -> Work:
        """The plan-path grad leg (see Collectives.plan_reduce_scatter):
        one native call over a precompiled sharded plan — pack, rs phase,
        shard compaction and the divisor in one GIL release. At
        ``wire=None`` the reduced shard is bit-identical to the matching
        slice of ``plan_allreduce(wire=None)``'s result (same partition,
        same phase body, same f32 divide)."""
        timeout_ms = _ms(self._timeout)
        if wire not in (None, "bf16", "q8"):
            raise ValueError(f"unsupported wire: {wire!r}")
        if ag_wire not in (None, "bf16"):
            raise ValueError(f"unsupported ag_wire: {ag_wire!r}")
        if op == ReduceOp.AVG:
            if divisor is not None:
                raise ValueError("divisor only composes with ReduceOp.SUM")
            divisor, op = float(self._world_size), ReduceOp.SUM
        if op != ReduceOp.SUM:
            raise ValueError("plan_reduce_scatter supports SUM/AVG only")
        return self._submit(
            lambda: self._plan_reduce_scatter_sync(
                tree, divisor, wire, ag_wire, timeout_ms
            )
        )

    def _sharded_plan_for(
        self, leaves: Sequence[Any], treedef: Any, wire: Optional[str],
        ag_wire: Optional[str],
    ) -> Optional[_ShardedPlan]:
        sig = tuple((l.shape, np.dtype(l.dtype)) for l in leaves)
        key: Any = (wire, ag_wire, treedef, sig, "sharded")
        if key in self._plans:
            return self._plans[key]
        try:
            plan: Optional[_ShardedPlan] = _ShardedPlan(
                self._handle, sig, treedef, wire, ag_wire,
                stripes=self._stripes, world=self._world_size,
            )
        except (KeyError, RuntimeError):
            # Non-f32 leaves (or a wire combination native rejects):
            # cache the verdict like the fused plan path.
            plan = None
        self._plans[key] = plan
        return plan

    def _plan_reduce_scatter_sync(
        self,
        tree: Any,
        divisor: Optional[float],
        wire: Optional[str],
        ag_wire: Optional[str],
        timeout_ms: int,
    ) -> TreeShard:
        leaves, treedef = _flatten(tree)
        if not leaves:
            raise ValueError("plan_reduce_scatter of an empty tree")
        plan = self._sharded_plan_for(leaves, treedef, wire, ag_wire)
        if plan is None:
            raise ValueError(
                "sharded comm plans take f32 leaves only (keep f32 master "
                "weights — the DiLoCo sharded-outer constraint — or use "
                "the fused plan path)"
            )
        t0 = time.perf_counter()
        staging_allocs = 0
        refs = []  # keep host views alive across the native call
        in_ptrs = plan.in_ptrs
        all_jax = True
        for i, l in enumerate(leaves):
            a = np.asarray(l)  # zero-copy for numpy / CPU jax leaves
            if not a.flags.c_contiguous:
                a = np.ascontiguousarray(a)
                staging_allocs += 1
            refs.append(a)
            in_ptrs[i] = a.ctypes.data
            all_jax = all_jax and _is_jax_array(l)
        t1 = time.perf_counter()
        # Shards double-buffer like plan outputs: the caller may still
        # hold step k's shard while step k+1 reduces; older shards are
        # clobbered.
        shard_buf = plan.shard_sets[plan.shard_flip]
        plan.shard_flip ^= 1
        _check(
            _lib.tft_plan_execute_rs(
                self._handle,
                plan.plan_id,
                in_ptrs,
                shard_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                float(divisor if divisor is not None else 1.0),
                0 if divisor is None else 1,
                timeout_ms,
            )
        )
        ring_s = time.perf_counter() - t1
        del refs
        plan.execs += 1
        t2 = time.perf_counter()
        values: Dict[str, Any] = {"float32": shard_buf}
        if all_jax:
            import jax.numpy as jnp

            values = {"float32": jnp.asarray(shard_buf)}
        self._record_op_stats({
            # Its own phase key: the grad leg bills separately from the
            # param leg (and from any fused plan op) in pop_op_stats.
            "op": "plan_reduce_scatter",
            "wire": wire,
            "bytes": plan.bytes,
            "shard_bytes": plan.shard_count * 4,
            "wire_bytes": plan.rs_wire_bytes,
            # the full tree crosses down once (when it started on
            # device); only the shard returns
            "d2h_bytes": plan.bytes if all_jax else 0,
            "d2h": t1 - t0,
            "ring": ring_s,
            "h2d": time.perf_counter() - t2,
            "_buckets_json": self._plan_stats_json(plan.plan_id),
            "py_staging_allocs": staging_allocs,
            "plan_execs": plan.execs,
        })
        return TreeShard(
            values=values,
            counts={"float32": plan.total},
            ranges={"float32": self._shard_ranges(plan.total, 4, plan.eff)},
            layout={"float32": plan.eff},
            dtypes={"float32": np.dtype(np.float32)},
            groups={"float32": list(range(len(leaves)))},
            treedef=treedef,
            sig=plan.sig,
            rank=self._rank,
            world_size=self._world_size,
            packer=None,
            was_jax=[_is_jax_array(l) for l in leaves],
            plan=plan,
        )

    def plan_allgather_into(
        self, shard: TreeShard, wire: Optional[str] = None
    ) -> Work:
        timeout_ms = _ms(self._timeout)
        if wire not in (None, "bf16"):
            raise ValueError(f"unsupported wire: {wire!r}")
        return self._submit(
            lambda: self._plan_allgather_into_sync(shard, wire, timeout_ms)
        )

    def _plan_allgather_into_sync(
        self, shard: TreeShard, wire: Optional[str], timeout_ms: int
    ) -> Any:
        """Param leg of the sharded plan: scatter the updated shard back,
        one ag phase at the plan's ag wire, unpack into the double-
        buffered output leaves. bf16: every member (owner included)
        adopts the identical decoded words — gathered params stay
        bit-identical across the cohort."""
        plan = shard.plan
        if plan is None:
            # A bulk-path TreeShard (reduce_scatter): same contract, bulk
            # ops serve it.
            return self._allgather_into_sync(shard, wire, timeout_ms)
        if wire != plan.ag_wire:
            raise ValueError(
                f"plan_allgather_into wire {wire!r} does not match the "
                f"plan's ag_wire {plan.ag_wire!r} (pre-declared at "
                "plan_reduce_scatter — the header pins it cohort-wide)"
            )
        vals = shard.values.get("float32")
        if vals is None or len(shard.values) != 1:
            raise ValueError(
                "pass the TreeShard from plan_reduce_scatter (values "
                "replaced, layout intact)"
            )
        t0 = time.perf_counter()
        d2h_bytes = 0
        if _is_jax_array(vals):
            d2h_bytes = np.asarray(vals).nbytes
        v = np.ascontiguousarray(np.asarray(vals))
        if v.dtype != np.dtype(np.float32):
            v = v.astype(np.float32)
        if v.size != plan.shard_count:
            raise ValueError(
                f"shard has {v.size} elements, the plan's layout expects "
                f"{plan.shard_count} — pass the TreeShard from "
                "plan_reduce_scatter (values replaced, layout intact)"
            )
        t1 = time.perf_counter()
        outs = plan.out_sets[plan.flip]
        out_ptrs = plan.out_ptrs[plan.flip]
        plan.flip ^= 1
        _check(
            _lib.tft_plan_execute_ag(
                self._handle,
                plan.plan_id,
                v.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                out_ptrs,
                timeout_ms,
            )
        )
        ring_s = time.perf_counter() - t1
        plan.execs += 1
        t2 = time.perf_counter()
        out_leaves: List[Any] = []
        for i in range(len(plan.sig)):
            leaf: Any = outs[i]
            if shard.was_jax is not None and shard.was_jax[i]:
                import jax.numpy as jnp

                leaf = jnp.asarray(leaf)
            out_leaves.append(leaf)
        out = _unflatten(shard.treedef, out_leaves)
        self._record_op_stats({
            # The param leg's own phase key, billed at the AG wire. Its
            # buckets (leg=2) append after the grad leg's (leg=1) in the
            # plan's stat window, so the pair reads as one step.
            "op": "plan_allgather_into",
            "wire": wire,
            "bytes": plan.bytes,
            "wire_bytes": plan.ag_wire_bytes,
            # only this rank's (updated) shard crosses down; the full
            # gathered tree returns on the h2d leg
            "d2h_bytes": d2h_bytes,
            "d2h": t1 - t0,
            "ring": ring_s,
            "h2d": time.perf_counter() - t2,
            "_buckets_json": self._plan_stats_json(plan.plan_id),
            "plan_execs": plan.execs,
        })
        return out

    def broadcast(self, tree: Any, root: int = 0) -> Work:
        timeout_ms = _ms(self._timeout)
        return self._submit(lambda: self._broadcast_sync(tree, root, timeout_ms))

    def _broadcast_sync(self, tree: Any, root: int, timeout_ms: int) -> Any:
        if self._world_size == 1:
            if root != 0:
                raise RuntimeError(f"bad broadcast root {root} for world size 1")
            return tree
        leaves, treedef = _flatten(tree)
        arrays = [np.ascontiguousarray(_as_numpy(l)) for l in leaves]
        was_jax = [_is_jax_array(l) for l in leaves]
        packed = bytearray(b"".join(a.tobytes() for a in arrays))
        nbytes = len(packed)
        buf = (ctypes.c_char * nbytes).from_buffer(packed) if nbytes else None
        _check(_lib.tft_hc_broadcast(self._handle, buf, nbytes, root, timeout_ms))
        offset = 0
        view = memoryview(packed)
        out_leaves: List[Any] = []
        for i, a in enumerate(arrays):
            size = a.nbytes
            out = (
                np.frombuffer(view[offset : offset + size], dtype=a.dtype)
                .reshape(a.shape)
                .copy()
            )
            offset += size
            if was_jax[i]:
                import jax.numpy as jnp

                out = jnp.asarray(out)
            out_leaves.append(out)
        return _unflatten(treedef, out_leaves)

    def barrier(self) -> Work:
        timeout_ms = _ms(self._timeout)
        return self._submit(
            lambda: _check(_lib.tft_hc_barrier(self._handle, timeout_ms))
        )


class DummyCollectives(Collectives):
    """No-op fake for tests and wrapper semantics, the reference's
    ProcessGroupDummy (torchft/process_group.py:333-384)."""

    def __init__(self, rank: int = 0, world_size: int = 1) -> None:
        self._rank = rank
        self._world_size = world_size
        self.configure_count = 0
        self.op_count = 0
        self.last_regions: Optional[List[str]] = None
        self.last_hosts: Optional[List[str]] = None
        self._hier = False

    def configure(
        self,
        store_addr: str,
        rank: int,
        world_size: int,
        regions: Optional[Sequence[str]] = None,
        hosts: Optional[Sequence[str]] = None,
    ) -> None:
        self.configure_count += 1
        self._rank = rank
        self._world_size = world_size
        self.last_regions = list(regions) if regions else None
        self.last_hosts = list(hosts) if hosts else None
        # Mirror the host ring's capability rule so wrapper-semantics
        # tests can drive the hier dispatch paths without a real ring:
        # multi-region, or a (region, host) pair grouping >= 2 ranks.
        multi_region = bool(
            regions
            and len(set(regions)) >= 2
            and all(regions)
            and world_size > 1
        )
        host_grouped = False
        if hosts and all(hosts) and world_size > 1:
            keys = [
                ((regions[i] if regions and all(regions) else ""), hosts[i])
                for i in range(len(hosts))
            ]
            host_grouped = any(keys.count(k) >= 2 for k in keys)
        self._hier = multi_region or host_grouped

    def hier_capable(self) -> bool:
        return self._hier

    def allreduce_hier(
        self,
        tree: Any,
        op: ReduceOp = ReduceOp.SUM,
        divisor: Optional[float] = None,
        wire: Optional[str] = None,
    ) -> Work:
        """Lossless fake of the two-tier schedule (sum of one member);
        raises without a usable region map, like the real backend."""
        if not self._hier and self._world_size > 1:
            raise RuntimeError("DummyCollectives: no region map configured")
        return self.allreduce(tree, op, divisor=divisor)

    def allreduce(
        self,
        tree: Any,
        op: ReduceOp = ReduceOp.SUM,
        divisor: Optional[float] = None,
        wire: Optional[str] = None,  # accepted, ignored (lossless fake)
    ) -> Work:
        self.op_count += 1
        if divisor is not None and divisor != 1:
            # The manager's AVG contract delegates the participant divide
            # to the backend; the fake must honor it or wrapper-semantics
            # tests see undivided gradients.
            import jax

            tree = jax.tree_util.tree_map(
                lambda l: _divide_leaf(l, divisor), tree
            )
        return _completed(tree)

    def plan_allreduce(
        self,
        tree: Any,
        op: ReduceOp = ReduceOp.SUM,
        divisor: Optional[float] = None,
        wire: Optional[str] = None,  # accepted, ignored (lossless fake)
        device_pack: Optional[bool] = None,  # accepted, ignored
        hier: bool = False,
    ) -> Work:
        """Same lossless semantics as the fake allreduce — wrapper tests
        exercise the plan-path call shape without a ring. ``hier``
        reproduces the real backend's capability rule (raises on a
        multi-member cohort without a usable region map)."""
        if op == ReduceOp.AVG:
            if divisor is not None:
                raise ValueError("divisor only composes with ReduceOp.SUM")
            divisor = float(self._world_size)
        if hier and not self._hier and self._world_size > 1:
            raise RuntimeError("DummyCollectives: no region map configured")
        return self.allreduce(tree, ReduceOp.SUM, divisor=divisor)

    def reduce_scatter(
        self,
        tree: Any,
        op: ReduceOp = ReduceOp.SUM,
        divisor: Optional[float] = None,
        wire: Optional[str] = None,
    ) -> Work:
        """Lossless fake: the 'shard' is the whole flat-packed tree (the
        world-size-1 shard layout), so reduce_scatter → update →
        allgather_into round-trips exactly."""
        self.op_count += 1
        leaves, treedef = _flatten(tree)
        sig = tuple((l.shape, np.dtype(l.dtype)) for l in leaves)
        flat = np.concatenate(
            [np.asarray(l).astype(np.float32, copy=False).ravel()
             for l in leaves]
        ) if leaves else np.zeros((0,), np.float32)
        if divisor is not None and divisor != 1:
            flat = flat / divisor
        name = str(np.dtype(np.float32))
        return _completed(TreeShard(
            values={name: flat},
            counts={name: flat.size},
            ranges={name: [(0, flat.size)]},
            layout={name: 1},
            dtypes={name: np.dtype(np.float32)},
            groups={name: list(range(len(leaves)))},
            treedef=treedef, sig=sig,
            rank=self._rank, world_size=self._world_size,
        ))

    def allgather_into(
        self, shard: TreeShard, wire: Optional[str] = None
    ) -> Work:
        self.op_count += 1
        name = str(np.dtype(np.float32))
        buf = np.asarray(shard.values[name])
        if wire == "bf16":
            buf = buf.astype(_BF16).astype(np.float32)
        out_leaves = []
        off = 0
        for shape, dt in shard.sig:
            n = int(np.prod(shape)) if shape else 1
            out_leaves.append(
                buf[off:off + n].reshape(shape).astype(dt, copy=False)
            )
            off += n
        return _completed(_unflatten(shard.treedef, out_leaves))

    def allgather(self, tree: Any) -> Work:
        self.op_count += 1
        return _completed([tree] * self._world_size)

    def broadcast(self, tree: Any, root: int = 0) -> Work:
        self.op_count += 1
        return _completed(tree)

    def barrier(self) -> Work:
        self.op_count += 1
        return _completed(None)

    def size(self) -> int:
        return self._world_size

    def rank(self) -> int:
        return self._rank
