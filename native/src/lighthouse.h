// Global quorum service. One per job; replica-group managers heartbeat (or
// batch-renew leases) into it and long-poll Quorum requests against it. Also
// the ROOT of the hierarchical tier: region lighthouses push membership
// digests into it and long-poll the global quorum back out. Serves an HTML
// dashboard plus a JSON status view on the same port (HTTP requests are
// sniffed apart from protocol frames). Reference: src/lighthouse.rs.
//
// DURABLE CONTROL PLANE (LighthouseOpt.wal_dir / peers / standby):
//
// - Write-ahead quorum log: with `wal_dir` set, every externally visible
//   promise (quorum commit, lease grant, explicit depart, root-epoch
//   claim) is appended to a CRC-framed WAL (see wal.h) BEFORE it is
//   published; restart replays snapshot+log to the exact pre-crash
//   quorum_id/quorum_gen watermark. A torn append kills the log and the
//   service stops forming NEW quorums (frozen promises beat regressed
//   ones) — reads, renewals and status keep serving.
//
// - Root epochs + warm standby: every ACTIVE claim (startup or standby
//   takeover) bumps a monotonic root epoch, fenced through the WAL. A
//   root started with `standby=true` (or fenced at startup by an active
//   peer holding a >= epoch) stays PASSIVE: it rejects the serving
//   protocol with UNAVAILABLE ("standby root ...", so clients rotate to
//   the next endpoint of their root list), tails the active peer's
//   membership through RootSync digests (the same age-relative entries
//   the region tier pushes), and takes over — epoch = max(seen)+1 —
//   when the active peer's lease lapses (`takeover_ms` without a
//   successful sync). An active root probes its peers and DEMOTES itself
//   when one reports active with a strictly higher epoch (the deposed
//   primary returning from a crash or stall fences instead of forking
//   the quorum history); a tick-loop stall longer than takeover_ms
//   forces that probe before any further promise is made.
#pragma once

#include <atomic>
#include <map>
#include <memory>
#include <string>
#include <thread>
#include <vector>

#include "conn_tracker.h"
#include "net.h"
#include "quorum.h"
#include "thread_annotations.h"
#include "wal.h"

namespace tft {

class Lighthouse {
 public:
  Lighthouse(const std::string& bind_addr, const LighthouseOpt& opt);
  ~Lighthouse();

  // "http://host:port" (dashboard is literally served over HTTP here).
  std::string address() const;
  uint16_t port() const;
  void shutdown();

  // Machine-readable status (the /status.json payload): members + lease
  // deadlines, last quorum, tier role, tick cost counters, region digests,
  // root epoch + WAL replay stamps, active/standby role.
  std::string status_json();

  // Whether this root is ACTIVE (serving quorums) vs a passive standby.
  bool active();
  // Monotonic root epoch (0 = never claimed active; epochs are bumped at
  // every active claim and fenced through the WAL when one is configured).
  int64_t root_epoch();

 private:
  void accept_loop();
  void tick_loop();
  void peer_loop();
  void handle_conn(Socket& sock);
  void handle_http(Socket& sock, const std::string& head);
  void handle_quorum_req(Socket& sock, const std::string& payload);
  void handle_lease_renew(Socket& sock, const std::string& payload);
  void handle_depart(Socket& sock, const std::string& payload);
  void handle_region_digest(Socket& sock, const std::string& payload);
  void handle_region_poll(Socket& sock, const std::string& payload);
  void handle_root_sync(Socket& sock, const std::string& payload);

  // Sends the standby rejection (UNAVAILABLE) when passive; returns true
  // when the caller must bail out.
  bool reject_if_standby(Socket& sock);

  // Runs one quorum check; called with mu_ held. On success publishes the new
  // quorum (bumping quorum_id only when membership changed) and wakes waiters.
  void quorum_tick_locked() TFT_REQUIRES(mu_);

  // WAL glue (no-ops without a wal_dir). wal_commit_quorum_locked returns
  // false when the promise could NOT be made durable (torn log) — the
  // caller must not publish it.
  bool wal_commit_quorum_locked(const torchft_tpu::Quorum& q)
      TFT_REQUIRES(mu_);
  void wal_log_members_locked(const std::vector<std::string>& ids)
      TFT_REQUIRES(mu_);
  // Synchronous best-effort replication of a freshly committed quorum to
  // the standby peers, BEFORE publication: the standby WAL-logs it and
  // acks, so a primary kill at any later instant finds the watermark
  // already replicated (the pull loop alone lags one sync interval).
  // Short-deadline and best-effort — a dead peer must not stall commits.
  void push_quorum_to_peers_locked(const torchft_tpu::Quorum& q)
      TFT_REQUIRES(mu_);

  // Peer-set plumbing (the root failover set).
  bool sync_from_peers();   // standby: pull state from the active peer
  void probe_peers_fence(); // active: demote behind a higher-epoch active
  void do_takeover();       // standby -> active (epoch bump, WAL-fenced)

  std::string render_status_locked() TFT_REQUIRES(mu_);
  Json status_json_locked() TFT_REQUIRES(mu_);

  LighthouseOpt opt_;
  std::unique_ptr<Listener> listener_;
  std::string hostname_;

  // Failover-set peers (parsed from opt_.peers; empty = classic single
  // root) and takeover bound. Immutable after construction.
  std::vector<std::string> peers_;
  int64_t takeover_ms_ = 3000;

  std::unique_ptr<DurableLog> wal_;  // null without wal_dir
  bool wal_replayed_ = false;        // restart restored pre-crash state
  int64_t wal_records_replayed_ = 0;
  int64_t wal_dropped_tail_bytes_ = 0;
  int64_t wal_replay_ms_ = 0;        // wall time of the recovery replay

  Mutex mu_;
  CondVar quorum_cv_;
  LighthouseState state_ TFT_GUARDED_BY(mu_);
  // Broadcast channel equivalent: monotone generation + latest value.
  int64_t quorum_gen_ TFT_GUARDED_BY(mu_) = 0;
  torchft_tpu::Quorum latest_quorum_ TFT_GUARDED_BY(mu_);

  // Role + fencing state. claim_nonce_ is the per-activation tie-break:
  // regenerated at every active claim, carried in RootSync responses —
  // two roots that end up at the SAME epoch (a restarted primary whose
  // startup probe missed the standby, or two simultaneously starving
  // standbys) fence on nonce order instead of both staying active.
  bool active_ TFT_GUARDED_BY(mu_) = true;
  int64_t root_epoch_ TFT_GUARDED_BY(mu_) = 0;
  uint64_t claim_nonce_ TFT_GUARDED_BY(mu_) = 0;
  int64_t seen_peer_epoch_ TFT_GUARDED_BY(mu_) = 0;
  int64_t last_sync_ok_ms_ TFT_GUARDED_BY(mu_) = 0;  // standby sync health
  int64_t wal_quorum_logged_ TFT_GUARDED_BY(mu_) = 0;  // standby qid ledger
  bool wal_dead_logged_ TFT_GUARDED_BY(mu_) = false;   // log-once flag

  // Region tier bookkeeping (status only; liveness rides the groups' own
  // forwarded leases, so a region's death needs no root-side timeout).
  struct RegionInfo {
    int64_t last_digest_ms = 0;
    int64_t entries = 0;
  };
  std::map<std::string, RegionInfo> regions_ TFT_GUARDED_BY(mu_);

  // Tick cost counters ("root CPU per tick" in LIGHTHOUSE_BENCH). Idle
  // ticks — no registered participant, so no quorum can possibly form —
  // skip the O(groups) membership scan entirely; that is the lease-based
  // replacement for the unconditional per-tick recompute.
  int64_t ticks_total_ TFT_GUARDED_BY(mu_) = 0;
  int64_t ticks_computed_ TFT_GUARDED_BY(mu_) = 0;
  int64_t last_compute_us_ TFT_GUARDED_BY(mu_) = 0;
  int64_t total_compute_us_ TFT_GUARDED_BY(mu_) = 0;
  int64_t last_tick_ms_ TFT_GUARDED_BY(mu_) = 0;  // stall-self-fence probe

  std::atomic<bool> shutting_down_{false};
  std::thread accept_thread_;
  std::thread tick_thread_;
  std::thread peer_thread_;
  ConnTracker conns_;
};

} // namespace tft
