"""graftlint: repo-specific static checks for torchft_tpu.

Machine-checks the cross-language contracts the codebase relies on but no
general-purpose linter can see:

- ``capi_sync``: every ``tft_*`` export in ``native/src/capi.cc`` has a
  matching ctypes declaration in ``torchft_tpu/_native.py`` (argument count
  and restype) and a stub in the ``_NativeLib`` block of
  ``torchft_tpu/_native.pyi`` — a three-way parse-and-diff of the bridge.
- ``latch_discipline``: every managed ``Manager.*`` collective routes
  through ``_managed_dispatch`` and never raises anything but an eager
  ``ValueError`` (data-plane failures must latch for the commit vote, not
  raise into the train loop).
- ``env_docs``: every ``TORCHFT_*`` knob read by the product code
  (``torchft_tpu/``, ``native/src/``) is documented in
  ``docs/OPERATIONS.md``.
- ``sleep_deadline``: no ``while``-loop in ``tests/`` polls with
  ``time.sleep`` unless the loop is visibly deadline-bounded.
- ``cache_mutation``: the plan cache (``HostCollectives._plans``) is only
  mutated inside its invalidation entry points.
- ``fault_guard``: every native chaos injection point reaches
  ``tft_fault_maybe`` through the ``TFT_FAULT_CHECK`` macro, preserving
  the disarmed single-relaxed-load fast path.
- ``proto_sync``: two-way field-name/field-number diff between
  ``native/torchft.proto`` and the handwritten
  ``native/src/pb_fallback/torchft.pb.h`` wire fallback (plus an
  internal AppendTo-vs-Field round-trip check).

Run via ``python scripts/graftlint.py`` (CI gates on it); extend by adding
a module under ``tools/graftlint/`` and registering it in ``RULES``.
"""

from __future__ import annotations

from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Dict, List


@dataclass(frozen=True)
class Violation:
    rule: str
    file: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.message}"


def relpath(root: Path, path: Path) -> str:
    """Path as displayed in violations: root-relative when under the root
    (the normal case), absolute otherwise (fixture files in tests)."""
    return str(path.relative_to(root)) if path.is_relative_to(root) else str(
        path
    )


def _load_rules() -> Dict[str, Callable[[Path], List[Violation]]]:
    from . import (
        cache_mutation,
        capi_sync,
        env_docs,
        fault_guard,
        latch_discipline,
        proto_sync,
        sleep_deadline,
    )

    return {
        "capi_sync": capi_sync.check,
        "latch_discipline": latch_discipline.check,
        "env_docs": env_docs.check,
        "sleep_deadline": sleep_deadline.check,
        "cache_mutation": cache_mutation.check,
        "fault_guard": fault_guard.check,
        "proto_sync": proto_sync.check,
    }


def run(root: Path, rules: List[str] | None = None) -> List[Violation]:
    """Runs the selected rules (default: all) against a repo root."""
    registry = _load_rules()
    selected = rules if rules else sorted(registry)
    out: List[Violation] = []
    for name in selected:
        if name not in registry:
            raise KeyError(
                f"unknown graftlint rule {name!r} (have: {sorted(registry)})"
            )
        out.extend(registry[name](root))
    return out
