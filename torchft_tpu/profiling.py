"""Tracing/profiling hooks: jax profiler spans around the FT transaction.

The reference has NO tracing or profiling subsystem (SURVEY.md §5:
"Tracing / profiling: none... Gap we may close on TPU with jax profiler
hooks") — observability there is logs + dashboard. This module closes the
gap the TPU-native way: the runtime's phase boundaries (quorum,
reconfigure, allreduce dispatch, checkpoint send/recv, commit vote) are
annotated with
``jax.profiler.TraceAnnotation`` spans so they appear on the host track of
a TensorBoard/XProf capture alongside XLA's device ops, and step
boundaries with ``StepTraceAnnotation`` so XProf's step-time breakdown
(compute vs host vs comms) works out of the box.

Capture is driven either programmatically::

    prof = Profiler(logdir="/tmp/trace", start_step=10, num_steps=5)
    manager = Manager(..., profiler=prof)   # or prof.on_step(step) by hand

or zero-code via environment variables (the config surface style of the
reference, SURVEY.md §5 config/flags)::

    TORCHFT_PROFILE_DIR=/tmp/trace TORCHFT_PROFILE_START=10 \
        TORCHFT_PROFILE_STEPS=5 python train.py

``span(name)`` is safe (and near-free) when no capture is active —
TraceAnnotation without an active session is a no-op — so the Manager
annotates unconditionally.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

logger = logging.getLogger(__name__)

_ENV_DIR = "TORCHFT_PROFILE_DIR"
_ENV_START = "TORCHFT_PROFILE_START"
_ENV_STEPS = "TORCHFT_PROFILE_STEPS"


def span(name: str):
    """Named host-track span; shows up in an active jax profiler capture.

    Usage: ``with span("torchft::quorum"): ...``
    """
    import jax.profiler

    return jax.profiler.TraceAnnotation(name)


def step_span(step: int):
    """XProf step annotation: ``with step_span(step): train_step(...)``."""
    import jax.profiler

    return jax.profiler.StepTraceAnnotation("torchft_step", step_num=step)


class Profiler:
    """Windowed jax profiler capture keyed on the manager's step counter.

    The capture starts when ``on_step(step)`` first sees
    ``step >= start_step`` and stops ``num_steps`` steps later (or at
    ``shutdown()``). Thread-safe; start/stop failures are logged, never
    raised — profiling must not take down training.
    """

    def __init__(
        self,
        logdir: str,
        start_step: int = 1,
        num_steps: int = 5,
    ) -> None:
        self.logdir = logdir
        self.start_step = start_step
        self.num_steps = num_steps
        self._lock = threading.Lock()
        self._state = "idle"  # idle -> active -> done
        self._stop_after: Optional[int] = None

    @classmethod
    def from_env(cls) -> Optional["Profiler"]:
        """Build from TORCHFT_PROFILE_* env vars; None when unset."""
        logdir = os.environ.get(_ENV_DIR)
        if not logdir:
            return None
        return cls(
            logdir,
            start_step=int(os.environ.get(_ENV_START, "1")),
            num_steps=int(os.environ.get(_ENV_STEPS, "5")),
        )

    def on_step(self, step: int) -> None:
        """Advance the capture window; called once per training step."""
        with self._lock:
            if self._state == "idle" and step >= self.start_step:
                self._start(step)
            elif (
                self._state == "active"
                and self._stop_after is not None
                and step >= self._stop_after
            ):
                self._stop()

    def shutdown(self) -> None:
        """Flush an in-flight capture (e.g. at trainer exit)."""
        with self._lock:
            if self._state == "active":
                self._stop()

    @property
    def state(self) -> str:
        return self._state

    # -- internal (lock held) --

    def _start(self, step: int) -> None:
        import jax.profiler

        try:
            jax.profiler.start_trace(self.logdir)
        except Exception as e:  # noqa: BLE001 - observability must not kill
            logger.warning("profiler start failed: %s", e)
            self._state = "done"
            return
        self._state = "active"
        # Window from the step the capture ACTUALLY started at — a replica
        # that resumes/heals past start_step still profiles num_steps.
        self._stop_after = step + self.num_steps
        logger.info(
            "profiling %d steps to %s", self.num_steps, self.logdir
        )

    def _stop(self) -> None:
        import jax.profiler

        try:
            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001
            logger.warning("profiler stop failed: %s", e)
        self._state = "done"
        logger.info("profile written to %s", self.logdir)
