"""Phase-level breakdown of one CPU ft_ddp step (2-process ring)."""
import json
import os
import sys
import time
from datetime import timedelta

os.environ["JAX_PLATFORMS"] = "cpu"
REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

from torchft_tpu.platform import apply_jax_platform_env

apply_jax_platform_env()

import bench

import jax
import numpy as np
import optax

from torchft_tpu import (
    FTTrainState,
    HostCollectives,
    Lighthouse,
    Manager,
    OptimizerWrapper,
)
from torchft_tpu.models import init_params, loss_fn

cfg, batch, _ = bench._model_setup()
os.environ["BENCH_FORCE_LAYERS"] = str(cfg.n_layers)
tx = optax.adamw(1e-3)
grad_fn = jax.jit(jax.value_and_grad(lambda p, b: loss_fn(cfg, p, b)))

lighthouse = Lighthouse(bind="[::]:0", min_replicas=1, join_timeout_ms=5000,
                        quorum_tick_ms=50)
steps, warm = 8, 2
peer = bench._spawn_peer(lighthouse.address(), warm + steps, "f32")
state = FTTrainState(init_params(cfg, jax.random.PRNGKey(0)), tx)
collectives = HostCollectives(timeout=timedelta(seconds=600))
manager = Manager(
    collectives=collectives,
    load_state_dict=state.load_state_dict,
    state_dict=state.state_dict,
    min_replica_size=1,
    timeout=timedelta(seconds=300),
    quorum_timeout=timedelta(seconds=300),
    rank=0,
    world_size=1,
    lighthouse_addr=lighthouse.address(),
    replica_id="bench_main_probe",
)
optimizer = OptimizerWrapper(manager, state)


def one(record=None):
    t0 = time.perf_counter()
    optimizer.zero_grad()
    t1 = time.perf_counter()
    loss, grads = grad_fn(state.params, batch)
    jax.block_until_ready(grads)
    t2 = time.perf_counter()
    work = manager.allreduce(grads)
    t3 = time.perf_counter()
    avg = work.wait()
    t4 = time.perf_counter()
    jax.block_until_ready(avg)
    t5 = time.perf_counter()
    optimizer.step(avg)
    jax.block_until_ready(state.params)
    t6 = time.perf_counter()
    if record is not None:
        record.append({
            "zero_grad": t1 - t0,
            "grad": t2 - t1,
            "dispatch": t3 - t2,
            "ring_wait": t4 - t3,
            "avg_ready": t5 - t4,
            "apply": t6 - t5,
            "total": t6 - t0,
        })


for _ in range(warm):
    one()
recs = []
for _ in range(steps):
    one(recs)
med = {k: round(sorted(r[k] for r in recs)[len(recs) // 2] * 1000, 1)
       for k in recs[0]}
print("median ms per phase:", json.dumps(med))
snap = manager.metrics().snapshot()
print("metrics:", json.dumps(snap, default=str))
assert collectives.size() == 2
peer.wait(timeout=120)
manager.shutdown()
collectives.shutdown()
lighthouse.shutdown()
