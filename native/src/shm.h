// POSIX shared-memory segments for the isolated accelerator data plane.
//
// The isolated XLA backend (torchft_tpu/isolated_xla.py) runs the
// jax.distributed runtime and its compiled collectives in a DISPOSABLE
// child process; gradient payloads never ride the command pipe — the
// parent lays them out into a shared-memory segment with the CommPlan
// leaf->offset discipline and the child maps the SAME bytes. A segment is
// therefore the one piece of state that must survive (and be reasoned
// about across) a child SIGKILL: POSIX shm is kernel-owned, so a killed
// child's mapping vanishes with it while the parent's mapping — and the
// bytes — stay intact, and the respawned child re-attaches by name.
//
// Lifecycle contract (the tft_shm_* C API mirrors it 1:1):
//   - Create(name, bytes): shm_open(O_CREAT|O_EXCL) + ftruncate + mmap.
//     The CREATOR owns the name: it unlinks on destruction (or explicitly
//     via Unlink) — attachments never do.
//   - Attach(name, bytes): shm_open existing + mmap; fails if the segment
//     is smaller than `bytes` (a truncated map would SIGBUS on touch).
//   - close/destroy: munmap + close(fd). The kernel frees the pages when
//     the last mapping AND the name are gone, so unlink-while-attached is
//     safe (the standard anonymous-after-rendezvous idiom).
//
// A process-wide registry (guarded, TSA-annotated) counts live segments
// so tests and the stress harness can assert leak-freedom after chaos
// rounds that abandon attachments the way a SIGKILLed child would.
#pragma once

#include <cstddef>
#include <cstdint>
#include <string>

#include "thread_annotations.h"

namespace tft {

class ShmSegment {
 public:
  // Creates (O_EXCL) or attaches a named segment; throws SocketError on
  // failure (name collision, ENOENT on attach, mmap failure). `name` is
  // normalized to the POSIX form (one leading '/').
  static ShmSegment* Create(const std::string& name, size_t bytes);
  static ShmSegment* Attach(const std::string& name, size_t bytes);
  ~ShmSegment();

  void* data() const { return data_; }
  size_t size() const { return size_; }
  const std::string& name() const { return name_; }

  // Removes the NAME (existing mappings stay valid). Idempotent: a
  // missing name is success — respawn paths unlink defensively.
  static void Unlink(const std::string& name);

  // Live ShmSegment handles in this process (both creators and
  // attachments) — the leak oracle for tests/stress.
  static int64_t live_count();

  ShmSegment(const ShmSegment&) = delete;
  ShmSegment& operator=(const ShmSegment&) = delete;

 private:
  ShmSegment(std::string name, void* data, size_t size, bool owner);

  std::string name_;
  void* data_;
  size_t size_;
  // Creator unlinks the name at destruction; attachments never do.
  const bool owner_;
};

// The CommPlan leaf->offset layout of a flat-packed signature, exported
// as JSON — the ONE authority both sides of the shm boundary lay out
// payloads with (the Python mirror `collectives._plan_groups` is pinned
// against this in tests). Replicates plan_build's grouping exactly:
// first-appearance order of the group dtype over leaves in signature
// order; q8 wires collapse f32/bf16 leaves into a single f32 group, the
// bf16 wire rides f32 leaves as bf16. Group bases are 64-byte aligned so
// typed views of the segment stay cache-line clean.
//
// Returns {"total_bytes": N,
//          "groups": [{"dtype": code, "offset": B, "count": C}],
//          "leaves": [{"group": g, "off": elemOff, "count": C}]}.
std::string shm_layout_json(const int64_t* counts, const int32_t* dtypes,
                            int64_t n_leaves, int wire);

}  // namespace tft
