"""Model + intra-group parallelism tests (8-device virtual CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torchft_tpu.models import (
    TransformerConfig,
    forward,
    init_params,
    loss_fn,
    param_sharding_rules,
    tiny_config,
)
from torchft_tpu.parallel import (
    build_apply_step,
    build_grad_step,
    make_mesh,
    replicate_pytree,
    shard_pytree,
)

from conftest import HAS_SHARD_MAP, SHARD_MAP_SKIP

# Tests that route through the shard_map'd flash/ring-attention kernels;
# the rest of this module runs fine on old jax.
requires_shard_map = pytest.mark.skipif(
    not HAS_SHARD_MAP, reason=SHARD_MAP_SKIP
)


@pytest.fixture(scope="module")
def cfg():
    return tiny_config()


@pytest.fixture(scope="module")
def params(cfg):
    return init_params(cfg, jax.random.PRNGKey(0))


class TestTransformer:
    def test_forward_shapes_and_finite(self, cfg, params):
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits = forward(cfg, params, tokens)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert logits.dtype == jnp.float32
        assert bool(jnp.all(jnp.isfinite(logits)))

    def test_loss_decreases_under_sgd(self, cfg, params):
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (4, 32)),
            jnp.int32,
        )
        tx = optax.adam(1e-2)
        opt_state = tx.init(params)
        grad_fn = jax.jit(jax.value_and_grad(lambda p, t: loss_fn(cfg, p, t)))
        losses = []
        p = params
        for _ in range(8):
            loss, grads = grad_fn(p, tokens)
            updates, opt_state = tx.update(grads, opt_state, p)
            p = optax.apply_updates(p, updates)
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_causality(self, cfg, params):
        # Changing a future token must not affect earlier logits.
        t1 = jnp.zeros((1, 8), jnp.int32)
        t2 = t1.at[0, 7].set(5)
        l1 = forward(cfg, params, t1)
        l2 = forward(cfg, params, t2)
        np.testing.assert_allclose(
            np.asarray(l1[:, :7]), np.asarray(l2[:, :7]), rtol=1e-4, atol=1e-4
        )

    def test_sharding_rules_match_params_structure(self, cfg, params):
        from jax.sharding import PartitionSpec

        rules = param_sharding_rules(cfg)
        td_p = jax.tree_util.tree_structure(params)
        td_r = jax.tree_util.tree_structure(
            rules, is_leaf=lambda l: isinstance(l, PartitionSpec)
        )
        assert td_p == td_r


class TestShardedTraining:
    def test_tp_dp_train_step_on_virtual_mesh(self, cfg):
        assert len(jax.devices()) >= 8
        mesh = make_mesh({"data": 2, "model": 4}, devices=jax.devices()[:8])
        rules = param_sharding_rules(cfg)
        params = shard_pytree(init_params(cfg, jax.random.PRNGKey(0)), rules, mesh)
        tx = optax.adamw(1e-3)
        opt_state = tx.init(params)
        grad_step = build_grad_step(
            lambda p, b: loss_fn(cfg, p, b), mesh, rules
        )
        apply_step = build_apply_step(tx)
        batch = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (4, 32)),
            jnp.int32,
        )
        loss, grads = grad_step(params, batch)
        params, opt_state = apply_step(params, opt_state, grads)
        assert np.isfinite(float(loss))

    def test_sharded_matches_single_device(self, cfg):
        # TP+DP sharding must not change the math (up to float tolerance).
        tokens = jnp.asarray(
            np.random.default_rng(1).integers(0, cfg.vocab_size, (4, 32)),
            jnp.int32,
        )
        params = init_params(cfg, jax.random.PRNGKey(0))
        expected = float(loss_fn(cfg, params, tokens))

        mesh = make_mesh({"data": 2, "model": 4}, devices=jax.devices()[:8])
        rules = param_sharding_rules(cfg)
        sharded = shard_pytree(params, rules, mesh)
        grad_step = build_grad_step(lambda p, b: loss_fn(cfg, p, b), mesh, rules)
        loss, _ = grad_step(sharded, tokens)
        assert abs(float(loss) - expected) < 5e-2  # bf16 matmul tolerance

    @requires_shard_map
    def test_context_parallel_train_step_dp_sp_tp(self, cfg):
        # Full 3D intra-group sharding: batch over "data", sequence ring
        # over "seq" (ring attention), heads over "model" — one jitted
        # step, loss matching the dense single-device model.
        import dataclasses

        from jax.sharding import PartitionSpec as P

        mesh = make_mesh(
            {"data": 2, "seq": 2, "model": 2}, devices=jax.devices()[:8]
        )
        cp_cfg = dataclasses.replace(
            cfg,
            cp_seq_axis="seq",
            cp_mesh=mesh,
            cp_head_axis="model",
        )
        tokens = jnp.asarray(
            np.random.default_rng(2).integers(0, cfg.vocab_size, (4, 33)),
            jnp.int32,
        )
        params = init_params(cfg, jax.random.PRNGKey(0))
        expected = float(loss_fn(cfg, params, tokens))

        rules = param_sharding_rules(cp_cfg)
        sharded = shard_pytree(params, rules, mesh)
        grad_step = build_grad_step(
            lambda p, b: loss_fn(cp_cfg, p, b), mesh, rules,
            batch_spec=P("data"),
        )
        loss, grads = grad_step(sharded, tokens)
        assert abs(float(loss) - expected) < 5e-2  # bf16 matmul tolerance
        for leaf in jax.tree_util.tree_leaves(grads):
            assert bool(jnp.all(jnp.isfinite(leaf)))

    def test_make_mesh_validates_sizes(self):
        with pytest.raises(ValueError):
            make_mesh({"data": 3, "model": 3}, devices=jax.devices()[:8])

    def test_replicate_pytree(self):
        mesh = make_mesh({"data": 8}, devices=jax.devices()[:8])
        tree = {"x": jnp.ones((4, 4))}
        out = replicate_pytree(tree, mesh)
        assert out["x"].sharding.is_fully_replicated


class TestGraftEntry:
    def test_entry_compiles(self):
        import __graft_entry__

        fn, args = __graft_entry__.entry()
        logits = jax.jit(fn)(*args)
        assert logits.shape[0] == args[1].shape[0]

    @requires_shard_map
    def test_dryrun_multichip(self):
        import __graft_entry__

        __graft_entry__.dryrun_multichip(8)


@requires_shard_map
def test_remat_policy_prunes_flash_fwd_recompute():
    """The point of save_attn + flash: the backward replay must NOT
    relaunch the forward flash kernel. Counted in the lowered HLO: one
    _fwd_kernel launch per layer with the policy, two without."""
    import dataclasses

    import numpy as np

    from torchft_tpu.models import init_params, loss_fn, tiny_config

    base = dataclasses.replace(tiny_config(), remat=True, use_flash=True)
    params = init_params(base, jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, base.vocab_size, (2, 33)),
        jnp.int32,
    )

    def pallas_calls(cfg):
        # jaxpr-level count (the CPU interpret lowering erases kernel
        # names from HLO); jaxpr text dedupes shared sub-jaxprs, so only
        # RELATIVE counts are meaningful. On the TPU lowering the HLO
        # shows exactly 2 fwd launches/layer plain vs 1 with the policy.
        jx = str(
            jax.make_jaxpr(jax.grad(lambda p: loss_fn(cfg, p, tokens)))(
                params
            )
        )
        return jx.count("pallas_call")

    plain = pallas_calls(base)
    saved = pallas_calls(
        dataclasses.replace(base, remat_policy="save_attn")
    )
    assert saved < plain, (saved, plain)


def test_bad_config_knobs_rejected():
    import dataclasses

    import pytest

    from torchft_tpu.models import tiny_config

    with pytest.raises(ValueError, match="cp_strategy"):
        dataclasses.replace(tiny_config(), cp_strategy="Ulysses")
    with pytest.raises(ValueError, match="remat_policy"):
        dataclasses.replace(tiny_config(), remat_policy="save-attn")


@requires_shard_map
def test_remat_policy_save_attn_matches_plain():
    """save_attn remat keeps numerics identical (it only changes what
    backward recomputes) for both dense and flash attention paths."""
    import dataclasses

    import numpy as np

    from torchft_tpu.models import init_params, loss_fn, tiny_config

    base = dataclasses.replace(tiny_config(), remat=True)
    params = init_params(base, jax.random.PRNGKey(0))
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, base.vocab_size, (2, 33)),
        jnp.int32,
    )
    for use_flash in (False, True):
        cfg = dataclasses.replace(base, use_flash=use_flash)
        cfg_pol = dataclasses.replace(cfg, remat_policy="save_attn")
        l_plain = float(loss_fn(cfg, params, tokens))
        l_pol = float(loss_fn(cfg_pol, params, tokens))
        np.testing.assert_allclose(l_pol, l_plain, rtol=1e-5, atol=1e-5)
        g_plain = jax.grad(lambda p: loss_fn(cfg, p, tokens))(params)
        g_pol = jax.grad(lambda p: loss_fn(cfg_pol, p, tokens))(params)
        for a, b in zip(
            jax.tree_util.tree_leaves(g_pol),
            jax.tree_util.tree_leaves(g_plain),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4,
                err_msg=f"use_flash={use_flash}",
            )


def test_bf16_params_master_copy_train_step():
    """make_train_step(bf16_params=True): the gradient pass reads a bf16
    working copy, the optimizer updates the f32 master — params stay f32,
    the loss trajectory tracks the f32 path closely, and training makes
    progress. VERDICT r3 item 1a (mixed precision with master weights)."""
    import numpy as np
    import optax

    from torchft_tpu.models import init_params, make_train_step, tiny_config

    cfg = tiny_config()
    tx = optax.adamw(1e-2)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 33)),
        jnp.int32,
    )
    losses = {}
    for bf16 in (False, True):
        step = make_train_step(cfg, tx, bf16_params=bf16)
        params = init_params(cfg, jax.random.PRNGKey(0))
        opt_state = tx.init(params)
        ls = []
        for _ in range(5):
            params, opt_state, loss = step(params, opt_state, tokens)
            ls.append(float(loss))
        losses[bf16] = ls
        # master stays f32 under the mixed path
        for leaf in jax.tree_util.tree_leaves(params):
            assert leaf.dtype == jnp.float32
        assert ls[-1] < ls[0]
    # same trajectory up to bf16 gradient-accumulation noise
    np.testing.assert_allclose(losses[True], losses[False], rtol=0.05)


def test_train_state_accepts_bf16_wire_grads():
    """FTTrainState.apply_gradients harmonizes lower-precision (wire)
    gradient dtypes with the f32 master before the optax update."""
    import numpy as np
    import optax

    from torchft_tpu.train_state import FTTrainState

    params = {"w": jnp.ones((4, 4), jnp.float32)}
    state = FTTrainState(params, optax.sgd(0.5))
    grads = {"w": jnp.full((4, 4), 0.5, jnp.bfloat16)}
    state.apply_gradients(grads)
    assert state.params["w"].dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(state.params["w"]), 0.75)
