"""Build hook compiling the native control plane into the package.

The reference compiles its Rust crate via maturin + build.rs
(reference pyproject.toml:1-3, build.rs:7-11); here the C++ control plane
(lighthouse, manager, store, ring collectives — native/src/) is built by
the Makefile and lands in the package as ``torchft_tpu/_libtorchft.so``,
loaded through ctypes (torchft_tpu/_native.py). Requires g++ (C++17),
protoc and libprotobuf.

Offline install (no index access)::

    pip install -e . --no-deps --no-build-isolation
"""

import os
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py


class build_py_with_native(build_py):
    def run(self):
        repo = os.path.dirname(os.path.abspath(__file__))
        subprocess.check_call(
            ["make", "-C", os.path.join(repo, "native"),
             f"-j{os.cpu_count() or 1}"]
        )
        super().run()


setup(cmdclass={"build_py": build_py_with_native})
