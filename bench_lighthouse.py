"""Control-plane scale bench: flat vs hierarchical lighthouse under churn.

Drives 1k-10k *simulated* replica groups — lightweight lease clients, no
training — against the quorum service and measures what the control plane
does as group count grows two orders of magnitude past a real job's:

- **flat**: every group renews its own lease over its OWN persistent
  connection straight into one lighthouse (today's per-group heartbeat
  model: fan-in = G connections, G renewal RPCs per interval).
- **hier**: groups renew in BATCHES into region lighthouses
  (``TORCHFT_LEASE_RENEW_BATCH`` entries per frame) which aggregate into
  the root via digests (fan-in at the root = 2 connections per region).

Churn: every settled quorum, one random group is killed (silent lease
expiry — the worst case; explicit departs are cheap) and the bench records
**quorum convergence**: kill -> first observed quorum that excludes the
dead group. Hier phases also kill a region lighthouse: its groups demote
to direct-root renewal (the same failover managers run) and the bench
records the failover window + whether any membership flapped.

Observation rides the lighthouse's machine-readable ``/status.json``
(torchft_tpu.lighthouse.fetch_status) — members, lease deadlines, quorum,
root tick cost, open connections — never the HTML dashboard.

Output: ``LIGHTHOUSE_BENCH.json`` with per-scale flat/hier convergence
p50/p99, heartbeat fan-in, renewal RPC counts and root CPU per tick.
``--dryrun`` runs a seconds-scale version (small group count, one group
kill + one region kill) and asserts a convergence record and a
region-failover record exist — the CI smoke.

Usage::

    python bench_lighthouse.py                     # full run, writes artifact
    python bench_lighthouse.py --scales 1000,4000 --regions 8
    python bench_lighthouse.py --dryrun            # CI smoke, no artifact
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time
from datetime import timedelta
from typing import Dict, List, Optional

from torchft_tpu import _native
from torchft_tpu.lighthouse import fetch_status


def member(replica_id: str, step: int = 1) -> dict:
    return {
        "replica_id": replica_id,
        "address": f"addr_{replica_id}",
        "store_address": f"store_{replica_id}",
        "step": step,
        "world_size": 1,
        "shrink_only": False,
        "force_reconfigure": False,
    }


def entry(replica_id: str, ttl_ms: int) -> dict:
    return {
        "replica_id": replica_id,
        "ttl_ms": ttl_ms,
        "participating": True,
        "member": member(replica_id),
    }


def percentile(values: List[float], p: float) -> Optional[float]:
    if not values:
        return None
    xs = sorted(values)
    i = min(len(xs) - 1, max(0, int(round(p / 100.0 * (len(xs) - 1)))))
    return xs[i]


class Phase:
    """One (mode, scale) run: renewal drivers + status watcher + churn."""

    def __init__(
        self,
        mode: str,
        n_groups: int,
        n_regions: int,
        ttl_ms: int,
        renew_interval_ms: int,
        batch: int,
        threads: int = 4,
    ) -> None:
        assert mode in ("flat", "hier")
        self.mode = mode
        self.n_groups = n_groups
        self.ttl_ms = ttl_ms
        self.renew_interval_ms = renew_interval_ms
        self.batch = batch
        self.threads = threads

        self.root = _native.Lighthouse(
            bind="[::]:0",
            min_replicas=1,
            join_timeout_ms=1000,
            quorum_tick_ms=50,
            heartbeat_timeout_ms=ttl_ms,
        )
        self.root_addr = self.root.address()
        self.regions: List[Optional[_native.RegionLighthouse]] = []
        self.region_dead: List[bool] = []
        if mode == "hier":
            for i in range(n_regions):
                self.regions.append(
                    _native.RegionLighthouse(
                        self.root_addr,
                        f"region_{i}",
                        digest_interval_ms=max(50, renew_interval_ms // 4),
                        heartbeat_timeout_ms=ttl_ms,
                    )
                )
                self.region_dead.append(False)

        self.groups = [f"g{i:05d}" for i in range(n_groups)]
        self.region_of = {g: i % max(1, len(self.regions)) for i, g in
                          enumerate(self.groups)}
        self.lock = threading.Lock()
        self.alive = set(self.groups)
        self.stop = threading.Event()
        self.renew_rpcs = 0
        self.renew_errors = 0
        self.samples: List[dict] = []  # watcher snapshots
        self._threads: List[threading.Thread] = []

    # -- renewal drivers --------------------------------------------------

    def _flat_driver(self, slice_groups: List[str], stagger_s: float) -> None:
        clients: Dict[str, _native.LeaseClient] = {}
        time.sleep(stagger_s)
        while not self.stop.is_set():
            t0 = time.monotonic()
            for g in slice_groups:
                if self.stop.is_set():
                    return
                with self.lock:
                    if g not in self.alive:
                        clients.pop(g, None)
                        continue
                try:
                    # one connection PER GROUP — the per-group heartbeat
                    # fan-in this mode exists to measure
                    if g not in clients:
                        clients[g] = _native.LeaseClient(
                            self.root_addr, connect_timeout=timedelta(seconds=5)
                        )
                    clients[g].renew(
                        [entry(g, self.ttl_ms)], timeout=timedelta(seconds=5)
                    )
                    with self.lock:
                        self.renew_rpcs += 1
                except Exception:  # noqa: BLE001
                    clients.pop(g, None)
                    with self.lock:
                        self.renew_errors += 1
            elapsed = time.monotonic() - t0
            self.stop.wait(max(0.0, self.renew_interval_ms / 1000.0 - elapsed))

    def _hier_driver(self, slice_groups: List[str], stagger_s: float) -> None:
        region_clients: Dict[int, _native.LeaseClient] = {}
        root_client: Optional[_native.LeaseClient] = None
        time.sleep(stagger_s)
        while not self.stop.is_set():
            t0 = time.monotonic()
            # bucket this slice's live groups by (current) target
            by_target: Dict[int, List[str]] = {}
            with self.lock:
                for g in slice_groups:
                    if g not in self.alive:
                        continue
                    r = self.region_of[g]
                    by_target.setdefault(-1 if self.region_dead[r] else r,
                                         []).append(g)
            for target, gs in by_target.items():
                for i in range(0, len(gs), self.batch):
                    if self.stop.is_set():
                        return
                    chunk = [entry(g, self.ttl_ms) for g in gs[i:i + self.batch]]
                    try:
                        if target < 0:
                            # demoted: direct-root registration (batched at
                            # host granularity, same as the region batched)
                            if root_client is None:
                                root_client = _native.LeaseClient(
                                    self.root_addr,
                                    connect_timeout=timedelta(seconds=5),
                                )
                            root_client.renew(chunk, timeout=timedelta(seconds=5))
                        else:
                            if target not in region_clients:
                                region_clients[target] = _native.LeaseClient(
                                    self.regions[target].address(),  # type: ignore[union-attr]
                                    connect_timeout=timedelta(seconds=5),
                                )
                            region_clients[target].renew(
                                chunk, timeout=timedelta(seconds=5)
                            )
                        with self.lock:
                            self.renew_rpcs += 1
                    except Exception:  # noqa: BLE001
                        with self.lock:
                            self.renew_errors += 1
                        if target >= 0:
                            region_clients.pop(target, None)
                            # region presumed dead: demote its groups until
                            # it is revived (manager-failover semantics),
                            # and retry THIS chunk at the root right away —
                            # the manager's own failover re-registers within
                            # a couple of heartbeat intervals, not a full
                            # lease interval later
                            with self.lock:
                                self.region_dead[target] = True
                            try:
                                if root_client is None:
                                    root_client = _native.LeaseClient(
                                        self.root_addr,
                                        connect_timeout=timedelta(seconds=5),
                                    )
                                root_client.renew(
                                    chunk, timeout=timedelta(seconds=5)
                                )
                                with self.lock:
                                    self.renew_rpcs += 1
                            except Exception:  # noqa: BLE001
                                with self.lock:
                                    self.renew_errors += 1
            elapsed = time.monotonic() - t0
            self.stop.wait(max(0.0, self.renew_interval_ms / 1000.0 - elapsed))

    def _watcher(self) -> None:
        while not self.stop.is_set():
            try:
                st = fetch_status(self.root_addr, timeout=5.0)
                q = st.get("quorum") or {}
                self.samples.append(
                    {
                        "t": time.monotonic(),
                        "quorum_id": st.get("quorum_id", 0),
                        "participants": sorted(
                            m["replica_id"] for m in q.get("participants", [])
                        ),
                        "members": {
                            m["replica_id"]: m["lease_remaining_ms"]
                            for m in st.get("members", [])
                        },
                        "open_conns": st.get("open_conns", 0),
                        "tick": st.get("tick", {}),
                    }
                )
            except Exception:  # noqa: BLE001
                pass
            self.stop.wait(0.05)

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        driver = self._flat_driver if self.mode == "flat" else self._hier_driver
        per = max(1, (len(self.groups) + self.threads - 1) // self.threads)
        for i in range(self.threads):
            sl = self.groups[i * per:(i + 1) * per]
            if not sl:
                continue
            t = threading.Thread(
                target=driver,
                args=(sl, i * self.renew_interval_ms / 1000.0 / self.threads),
                daemon=True,
            )
            t.start()
            self._threads.append(t)
        w = threading.Thread(target=self._watcher, daemon=True)
        w.start()
        self._threads.append(w)

    def shutdown(self) -> None:
        self.stop.set()
        for t in self._threads:
            t.join(timeout=10)
        for r in self.regions:
            if r is not None:
                r.shutdown()
        self.root.shutdown()

    # -- observation helpers ----------------------------------------------

    def latest(self) -> Optional[dict]:
        return self.samples[-1] if self.samples else None

    def wait_for(self, pred, deadline_s: float) -> Optional[dict]:
        """First watcher sample taken from NOW on satisfying pred (stale
        samples must not satisfy a churn probe), or None on timeout."""
        start = time.monotonic()
        deadline = start + deadline_s
        n = len(self.samples)
        while time.monotonic() < deadline:
            samples = self.samples
            while n < len(samples):
                s = samples[n]
                n += 1
                if s["t"] >= start and pred(s):
                    return s
            time.sleep(0.02)
        return None

    def wait_full_quorum(self, deadline_s: float) -> Optional[dict]:
        with self.lock:
            want = set(self.alive)
        return self.wait_for(
            lambda s: set(s["participants"]) == want, deadline_s
        )

    # -- churn ------------------------------------------------------------

    def kill_group(self, rng: random.Random, deadline_s: float) -> Optional[float]:
        """Silent-kills one group; returns convergence seconds or None."""
        with self.lock:
            victim = rng.choice(sorted(self.alive))
            self.alive.discard(victim)
        t0 = time.monotonic()
        base = self.latest()
        base_id = base["quorum_id"] if base else 0
        s = self.wait_for(
            lambda s: s["quorum_id"] > base_id
            and victim not in s["participants"]
            and s["participants"],
            deadline_s,
        )
        conv = None if s is None else s["t"] - t0
        # revive under the same id (constant scale) and wait to settle
        with self.lock:
            self.alive.add(victim)
        self.wait_full_quorum(deadline_s)
        return conv

    def kill_region(self, idx: int, deadline_s: float) -> Optional[dict]:
        """Kills a region lighthouse; returns a failover record or None.

        Failover is complete when every one of the region's groups has a
        FRESH direct-root lease (renewed after the kill). Membership flaps
        (a lease expiring mid-failover) are recorded honestly.
        """
        region = self.regions[idx]
        assert region is not None
        affected = [g for g in self.groups if self.region_of[g] == idx]
        t0 = time.monotonic()
        base = self.latest()
        base_id = base["quorum_id"] if base else 0
        region.shutdown()
        # drivers discover the death on their next renewal and demote

        def recovered(s: dict) -> bool:
            # A lease renewed at t_r shows remaining = ttl - (t_sample-t_r);
            # requiring remaining > ttl - (t_sample - t_kill) + margin means
            # t_r is provably AFTER the kill — i.e. the group's renewals are
            # flowing over the direct-root path, not riding a stale lease.
            elapsed_ms = (s["t"] - t0) * 1000.0
            need = self.ttl_ms - elapsed_ms + 100.0
            return all(s["members"].get(g, -1) > need for g in affected)

        s = self.wait_for(recovered, deadline_s)
        rec = None
        if s is not None:
            latest = self.latest() or s
            rec = {
                "region": idx,
                "groups": len(affected),
                "failover_s": s["t"] - t0,
                # quorum_id moved iff some lease expired mid-failover
                "membership_flapped": latest["quorum_id"] > base_id,
            }
        # revive: fresh region on a fresh port; drivers route back
        self.regions[idx] = _native.RegionLighthouse(
            self.root_addr,
            f"region_{idx}",
            digest_interval_ms=max(50, self.renew_interval_ms // 4),
            heartbeat_timeout_ms=self.ttl_ms,
        )
        with self.lock:
            self.region_dead[idx] = False
        self.wait_full_quorum(deadline_s)
        return rec


def run_root_outage_phase(n_groups: int, args: argparse.Namespace) -> dict:
    """Durable-control-plane bench: primary + warm-standby ROOT
    SUBPROCESSES (both WAL'd) behind a region tier, ``n_groups``
    simulated groups renewing in batches. Measures:

    - **takeover**: SIGKILL the primary -> first observed sample where
      the standby is ACTIVE and every group's lease is FRESH (renewed
      after the kill, i.e. the whole fleet re-registered through the
      failover set without any group restart), plus the quorum_id
      watermark continuity across the epoch bump.
    - **restart replay**: restart the killed primary on its WAL ->
      status-reported replay wall time + record count, and the fencing
      verdict (it must come back PASSIVE behind the takeover epoch).
    """
    import tempfile

    from torchft_tpu.chaos import RootProcess, free_port

    ports = [free_port(), free_port()]
    addrs = [f"http://localhost:{p}" for p in ports]
    roots_list = ",".join(addrs)
    wal_dirs = [tempfile.mkdtemp(prefix="tft_lhb_wal_") for _ in ports]
    takeover_ms = args.takeover_ms
    primary = RootProcess(
        ports[0], wal_dir=wal_dirs[0], peers=addrs[1],
        takeover_ms=takeover_ms, heartbeat_timeout_ms=args.ttl_ms,
        join_timeout_ms=1000,
    )
    standby = RootProcess(
        ports[1], wal_dir=wal_dirs[1], peers=addrs[0], standby=True,
        takeover_ms=takeover_ms, heartbeat_timeout_ms=args.ttl_ms,
        join_timeout_ms=1000,
    )
    primary.wait_serving()
    standby.wait_serving()

    regions = [
        _native.RegionLighthouse(
            roots_list,
            f"region_{i}",
            digest_interval_ms=max(50, args.renew_interval_ms // 4),
            heartbeat_timeout_ms=args.ttl_ms,
        )
        for i in range(args.regions)
    ]
    groups = [f"g{i:05d}" for i in range(n_groups)]
    region_of = {g: i % len(regions) for i, g in enumerate(groups)}
    stop = threading.Event()
    samples: List[dict] = []
    out: dict = {"phase": "root_outage", "groups": n_groups,
                 "regions": args.regions, "takeover_ms_bound": takeover_ms}

    def driver(slice_groups: List[str], stagger_s: float) -> None:
        clients: Dict[int, _native.LeaseClient] = {}
        time.sleep(stagger_s)
        while not stop.is_set():
            t0 = time.monotonic()
            by_region: Dict[int, List[str]] = {}
            for g in slice_groups:
                by_region.setdefault(region_of[g], []).append(g)
            for r, gs in by_region.items():
                for i in range(0, len(gs), args.batch):
                    if stop.is_set():
                        return
                    chunk = [entry(g, args.ttl_ms) for g in gs[i:i + args.batch]]
                    try:
                        if r not in clients:
                            clients[r] = _native.LeaseClient(
                                regions[r].address(),
                                connect_timeout=timedelta(seconds=5),
                            )
                        clients[r].renew(chunk, timeout=timedelta(seconds=5))
                    except Exception:  # noqa: BLE001
                        clients.pop(r, None)
            elapsed = time.monotonic() - t0
            stop.wait(max(0.0, args.renew_interval_ms / 1000.0 - elapsed))

    def watcher() -> None:
        while not stop.is_set():
            for idx, root in enumerate((primary, standby)):
                st = root.status(timeout=2.0)
                if st is None:
                    continue
                samples.append(
                    {
                        "t": time.monotonic(),
                        "endpoint": idx,
                        "active": st.get("active", False),
                        "root_epoch": st.get("root_epoch", 0),
                        "quorum_id": st.get("quorum_id", 0),
                        "members": {
                            m["replica_id"]: m["lease_remaining_ms"]
                            for m in st.get("members", [])
                        },
                    }
                )
            stop.wait(0.05)

    threads: List[threading.Thread] = []
    per = max(1, (n_groups + args.threads - 1) // args.threads)
    for i in range(args.threads):
        sl = groups[i * per:(i + 1) * per]
        if sl:
            t = threading.Thread(
                target=driver,
                args=(sl, i * args.renew_interval_ms / 1000.0 / args.threads),
                daemon=True,
            )
            t.start()
            threads.append(t)
    w = threading.Thread(target=watcher, daemon=True)
    w.start()
    threads.append(w)

    def wait_sample(pred, deadline_s: float) -> Optional[dict]:
        start = time.monotonic()
        n = len(samples)
        while time.monotonic() < start + deadline_s:
            cur = samples
            while n < len(cur):
                s = cur[n]
                n += 1
                if s["t"] >= start and pred(s):
                    return s
            time.sleep(0.02)
        return None

    deadline = max(30.0, 3 * args.ttl_ms / 1000.0 + 0.002 * n_groups)
    try:
        want = set(groups)
        t_start = time.monotonic()
        warm = wait_sample(
            lambda s: s["active"] and set(s["members"]) >= want,
            4 * deadline,
        )
        if warm is None:
            out["error"] = "fleet never fully leased at the primary"
            return out
        out["warmup_s"] = round(warm["t"] - t_start, 3)
        qid_before = warm["quorum_id"]
        epoch_before = warm["root_epoch"]

        # ---- takeover: SIGKILL the primary ----
        t_kill = time.monotonic()
        primary.kill()

        def taken_over(s: dict) -> bool:
            if s["endpoint"] != 1 or not s["active"]:
                return False
            elapsed_ms = (s["t"] - t_kill) * 1000.0
            need = args.ttl_ms - elapsed_ms + 100.0
            return all(s["members"].get(g, -1) > need for g in want)

        s = wait_sample(taken_over, 2 * deadline)
        if s is None:
            out["error"] = "standby never took over with fresh fleet leases"
            return out
        out["takeover_s"] = round(s["t"] - t_kill, 3)
        out["epoch_before"] = epoch_before
        out["epoch_after"] = s["root_epoch"]
        out["quorum_id_before"] = qid_before
        out["quorum_id_after"] = s["quorum_id"]
        out["watermark_monotone"] = s["quorum_id"] >= qid_before

        # ---- restart replay: revive the primary on its WAL ----
        t_restart = time.monotonic()
        primary.restart()
        st = primary.wait_serving(deadline_s=60)
        out["restart_serving_s"] = round(time.monotonic() - t_restart, 3)
        wal = st.get("wal", {})
        out["restart_wal_replayed"] = st.get("wal_replayed", False)
        out["restart_replay_ms"] = wal.get("replay_ms")
        out["restart_records_replayed"] = wal.get("records_replayed")
        out["restart_fenced_standby"] = not st.get("active", True)
        out["restart_root_epoch"] = st.get("root_epoch")
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        for r in regions:
            r.shutdown()
        primary.stop()
        standby.stop()
        import shutil

        for d in wal_dirs:
            shutil.rmtree(d, ignore_errors=True)
    return out


def run_phase(
    mode: str,
    n_groups: int,
    args: argparse.Namespace,
    rng: random.Random,
) -> dict:
    phase = Phase(
        mode,
        n_groups,
        args.regions,
        args.ttl_ms,
        args.renew_interval_ms,
        args.batch,
        threads=args.threads,
    )
    out: dict = {
        "mode": mode,
        "groups": n_groups,
        "regions": args.regions if mode == "hier" else 0,
        "converged": False,
        "convergence_s": [],
        "region_failovers": [],
    }
    deadline = max(30.0, 3 * args.ttl_ms / 1000.0 + 0.002 * n_groups)
    try:
        phase.start()
        t_warm = time.monotonic()
        warm = phase.wait_full_quorum(deadline_s=4 * deadline)
        if warm is None:
            # the scale this mode cannot sustain — itself a result; keep
            # the load metrics as evidence of WHERE it collapsed
            out["error"] = "never reached a full quorum (warmup)"
            tail = phase.samples[-20:]
            if tail:
                out["fan_in_conns"] = max(s["open_conns"] for s in tail)
                out["max_participants_seen"] = max(
                    len(s["participants"]) for s in phase.samples
                )
                out["members_alive_last"] = sum(
                    1 for v in tail[-1]["members"].values() if v > 0
                )
            with phase.lock:
                out["renew_rpcs"] = phase.renew_rpcs
                out["renew_errors"] = phase.renew_errors
            return out
        out["converged"] = True
        out["warmup_s"] = round(warm["t"] - t_warm, 3)

        for _ in range(args.kills):
            conv = phase.kill_group(rng, deadline_s=2 * deadline)
            if conv is not None:
                out["convergence_s"].append(round(conv, 3))
        if mode == "hier":
            for _ in range(args.region_kills):
                rec = phase.kill_region(
                    rng.randrange(args.regions), deadline_s=2 * deadline
                )
                if rec is not None:
                    rec["failover_s"] = round(rec["failover_s"], 3)
                    out["region_failovers"].append(rec)

        # steady-state + load metrics off the watcher tail
        tail = phase.samples[-20:]
        out["fan_in_conns"] = max(s["open_conns"] for s in tail)
        ticks = [s["tick"] for s in tail if s["tick"]]
        if ticks:
            t0, t1 = ticks[0], ticks[-1]
            computed = t1.get("computed", 0) - t0.get("computed", 0)
            us = t1.get("total_compute_us", 0) - t0.get("total_compute_us", 0)
            out["tick"] = {
                "computed_per_s": round(
                    computed / max(1e-9, tail[-1]["t"] - tail[0]["t"]), 2
                ),
                "mean_compute_us": round(us / computed, 1) if computed else 0.0,
                "last_compute_us": t1.get("last_compute_us", 0),
            }
        with phase.lock:
            out["renew_rpcs"] = phase.renew_rpcs
            out["renew_errors"] = phase.renew_errors
        cs = out["convergence_s"]
        out["convergence_p50_s"] = percentile(cs, 50)
        out["convergence_p99_s"] = percentile(cs, 99)
    finally:
        phase.shutdown()
    return out


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    p.add_argument("--scales", default="1000,2000",
                   help="comma-separated simulated group counts")
    p.add_argument("--regions", type=int, default=8)
    p.add_argument("--ttl-ms", type=int, default=3000)
    p.add_argument("--renew-interval-ms", type=int, default=1000)
    p.add_argument(
        "--batch",
        type=int,
        default=int(os.environ.get("TORCHFT_LEASE_RENEW_BATCH", "64")),
        help="lease entries per renewal frame in hier mode "
        "(env TORCHFT_LEASE_RENEW_BATCH)",
    )
    p.add_argument("--threads", type=int, default=4)
    p.add_argument("--kills", type=int, default=6)
    p.add_argument("--region-kills", type=int, default=1)
    p.add_argument(
        "--takeover-ms",
        type=int,
        default=1500,
        help="standby takeover bound for the root-outage phase "
        "(TORCHFT_LH_TAKEOVER_MS on the spawned roots)",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default="LIGHTHOUSE_BENCH.json")
    p.add_argument(
        "--dryrun",
        action="store_true",
        help="seconds-scale smoke: small group count, one group kill + one "
        "region kill + one root kill/restart, asserts convergence, "
        "region-failover and root-takeover records, writes NO artifact",
    )
    p.add_argument(
        "--root-outage-only",
        action="store_true",
        help="run ONLY the root-outage phase per scale and merge its "
        "records into an existing artifact (the flat/hier scale phases "
        "are expensive; the durability phase can be refreshed alone)",
    )
    args = p.parse_args(argv)

    if args.dryrun:
        args.scales = "40"
        args.regions = 2
        args.ttl_ms = 1200
        args.renew_interval_ms = 300
        args.kills = 1
        args.region_kills = 1
        args.threads = 2

    rng = random.Random(args.seed)
    scales = [int(s) for s in args.scales.split(",") if s]

    if args.root_outage_only:
        try:
            with open(args.out) as fp:
                result = json.load(fp)
        except (OSError, json.JSONDecodeError):
            result = {"bench": "lighthouse", "scales": []}
        by_groups = {row.get("groups"): row for row in result.get("scales", [])}
        for n in scales:
            print(f"=== root_outage @ {n} groups ===", flush=True)
            rec = run_root_outage_phase(n, args)
            print(json.dumps(rec), flush=True)
            row = by_groups.get(n)
            if row is None:
                row = {"groups": n}
                result.setdefault("scales", []).append(row)
                by_groups[n] = row
            row["root_outage"] = rec
        result.setdefault("config", {})["takeover_ms"] = args.takeover_ms
        with open(args.out, "w") as fp:
            json.dump(result, fp, indent=2, sort_keys=True)
        print(f"wrote {args.out}")
        return 0
    result = {
        "bench": "lighthouse",
        "host": {"cpus": os.cpu_count()},
        "config": {
            "regions": args.regions,
            "ttl_ms": args.ttl_ms,
            "renew_interval_ms": args.renew_interval_ms,
            "batch": args.batch,
            "kills": args.kills,
            "region_kills": args.region_kills,
            "threads": args.threads,
            "seed": args.seed,
        },
        "scales": [],
    }

    for n in scales:
        row: dict = {"groups": n}
        for mode in ("flat", "hier"):
            print(f"=== {mode} @ {n} groups ===", flush=True)
            row[mode] = run_phase(mode, n, args, rng)
            print(json.dumps(row[mode]), flush=True)
        print(f"=== root_outage @ {n} groups ===", flush=True)
        row["root_outage"] = run_root_outage_phase(n, args)
        print(json.dumps(row["root_outage"]), flush=True)
        f, h = row["flat"], row["hier"]
        if f.get("convergence_p99_s") is not None and h.get(
            "convergence_p99_s"
        ) is not None:
            row["hier_p99_not_worse"] = (
                h["convergence_p99_s"]
                <= f["convergence_p99_s"] + 0.25 * f["convergence_p99_s"] + 0.2
            )
        result["scales"].append(row)

    if args.dryrun:
        row = result["scales"][0]
        assert row["flat"]["convergence_s"], "no flat convergence record"
        assert row["hier"]["convergence_s"], "no hier convergence record"
        assert row["hier"]["region_failovers"], "no region-failover record"
        ro = row["root_outage"]
        assert "takeover_s" in ro, f"no root takeover record: {ro}"
        assert ro["watermark_monotone"], f"takeover regressed quorum_id: {ro}"
        assert ro["restart_wal_replayed"] and ro["restart_fenced_standby"], (
            f"restarted primary did not replay+fence: {ro}"
        )
        print(
            "dryrun OK: convergence + region-failover + root-takeover "
            "records present"
        )
        return 0

    with open(args.out, "w") as fp:
        json.dump(result, fp, indent=2, sort_keys=True)
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
