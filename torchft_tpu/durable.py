"""Durable periodic checkpoints: the save/restore discipline the runtime
requires, packaged.

The reference leaves durable checkpoints to the user but pins the
contract: "when saving periodic checkpoints you must save and restore the
Manager's state_dict as well" (reference manager.py:83-85), and its demo
checkpoints the dataloader position per replica group every step
(reference train_ddp.py:141-148). Getting this wrong is silent: restore
user weights without the manager's ``{step, batches_committed}`` and the
replica rejoins at step 0, triggering a spurious heal; restore without
the loader position and data repeats or skips.

:class:`DurableCheckpointer` bundles all three into one atomic-rename
file per checkpoint:

    ckpt = DurableCheckpointer(dir_, manager, state, loader=loader,
                               every=100, keep=3)
    ckpt.restore_latest()          # before the first quorum
    while ...:
        optimizer.zero_grad(); ...; optimizer.step(avg)
        ckpt.maybe_save()          # no-op except on every-th COMMITTED step

Serialization is the framework's safelisted-pickle format
(checkpointing.serialize_state_dict — plain numpy leaves + treedef), the
same bytes the live-recovery transport ships; files are written to a
temp name and atomically renamed so a crash mid-write never corrupts the
latest checkpoint. Retention keeps the newest ``keep`` files.
"""

from __future__ import annotations

import logging
import os
import re
from typing import Any, Optional

from .checkpointing import deserialize_state_dict, serialize_state_dict

logger = logging.getLogger(__name__)

_FILE_RE = re.compile(r"^step_(\d+)\.ckpt$")


class DurableCheckpointer:
    """Periodic durable checkpoints of (user state, manager state, loader
    position), restore-aware of the commit discipline."""

    def __init__(
        self,
        directory: str,
        manager: Any,
        state: Any,
        *,
        loader: Any = None,
        every: int = 100,
        keep: int = 3,
    ) -> None:
        """
        Args:
            directory: checkpoint dir (created if missing).
            manager: the Manager; its state_dict/load_state_dict carry
                ``{step, batches_committed}``.
            state: object with ``state_dict()``/``load_state_dict()``
                for USER state (e.g. FTTrainState or a LocalSGD algo).
            loader: optional StatefulDataLoader (position checkpointed).
            every: save on every ``every``-th committed step.
            keep: newest files retained.
        """
        self._dir = directory
        self._manager = manager
        self._state = state
        self._loader = loader
        self._every = max(int(every), 1)
        self._keep = max(int(keep), 1)
        self._last_saved: Optional[int] = None
        os.makedirs(directory, exist_ok=True)

    # -- save --

    def maybe_save(self) -> Optional[str]:
        """Saves iff the manager just committed an ``every``-boundary
        step; call right after ``optimizer.step``. Returns the path when
        a checkpoint was written."""
        step = self._manager.current_step()
        # step only advances on COMMIT: after an aborted step the loop
        # lands here again at the same step — re-saving would overwrite a
        # good checkpoint with a loader position that already consumed
        # the aborted batch (silent data skip on restore)
        if step == 0 or step % self._every or step == self._last_saved:
            return None
        return self.save()

    def save(self) -> str:
        """Unconditional checkpoint of the current state."""
        step = self._manager.current_step()
        payload = {
            "user": self._state.state_dict(),
            "torchft": self._manager.state_dict(),
        }
        if self._loader is not None:
            payload["loader"] = self._loader.state_dict()
        raw = serialize_state_dict(payload)
        path = os.path.join(self._dir, f"step_{step}.ckpt")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(raw)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)  # atomic: a crash never corrupts 'latest'
        logger.info("durable checkpoint: %s (%d bytes)", path, len(raw))
        self._last_saved = step
        self._retain()
        return path

    # -- restore --

    def restore_latest(self) -> Optional[int]:
        """Restores the newest checkpoint; returns its step, or None when
        the directory has none. Call BEFORE the first quorum so the
        replica joins at its restored step instead of 0."""
        latest = self.latest_path()
        if latest is None:
            return None
        with open(latest, "rb") as f:
            payload = deserialize_state_dict(f.read())
        self._state.load_state_dict(payload["user"])
        self._manager.load_state_dict(payload["torchft"])
        if self._loader is not None and "loader" in payload:
            self._loader.load_state_dict(payload["loader"])
        step = int(payload["torchft"]["step"])
        # Arm the same-step guard for the restored step too: an aborted
        # first post-restore step must not overwrite this checkpoint with
        # a drifted loader position.
        self._last_saved = step
        logger.info("restored durable checkpoint %s (step %d)", latest, step)
        return step

    def latest_path(self) -> Optional[str]:
        steps = self._list_steps()
        if not steps:
            return None
        return os.path.join(self._dir, f"step_{steps[-1]}.ckpt")

    # -- internal --

    def _list_steps(self) -> list:
        steps = []
        for name in os.listdir(self._dir):
            m = _FILE_RE.match(name)
            if m:
                steps.append(int(m.group(1)))
        return sorted(steps)

    def _retain(self) -> None:
        steps = self._list_steps()
        for s in steps[: -self._keep]:
            try:
                os.unlink(os.path.join(self._dir, f"step_{s}.ckpt"))
            except OSError:  # pragma: no cover - best-effort retention
                pass
