"""Live-server tests for the native control plane.

Mirrors the reference's in-process gRPC e2e tests
(reference src/lighthouse.rs:910-952,1036-1140; src/manager.rs:504-660)
and the fast-fail timeout bounds (reference torchft/manager_integ_test.py:356-368,
torchft/lighthouse_test.py:44-47).
"""

import subprocess
import sys
import threading
import time
from datetime import timedelta

import pytest

from torchft_tpu._native import (
    Lighthouse,
    Manager,
    ManagerClient,
    Store,
    StoreClient,
    lighthouse_heartbeat,
)

TIMEOUT = timedelta(seconds=20)


@pytest.fixture
def lighthouse():
    lh = Lighthouse(min_replicas=1, join_timeout_ms=100)
    yield lh
    lh.shutdown()


def _quorum_threads(clients_steps, shrink_only=None):
    """Run quorum() for several (name, client, step) tuples concurrently."""
    results, errors = {}, {}

    def run(name, client, step):
        try:
            results[name] = client.quorum(
                0,
                step,
                f"ckpt-{name}",
                shrink_only=bool(shrink_only and name in shrink_only),
                timeout=TIMEOUT,
            )
        except Exception as e: # noqa: BLE001
            errors[name] = e

    threads = [
        threading.Thread(target=run, args=t, daemon=True) for t in clients_steps
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors, errors
    return results


class TestStore:
    def test_set_get_add(self):
        store = Store()
        client = StoreClient(store.address())
        client.set("k", b"v")
        assert client.get("k") == b"v"
        assert client.add("n", 2) == 2
        assert client.add("n", 40) == 42
        store.shutdown()

    def test_get_timeout_bound(self):
        store = Store()
        client = StoreClient(store.address())
        start = time.monotonic()
        with pytest.raises(TimeoutError):
            client.get("never", timeout=timedelta(milliseconds=50))
        assert time.monotonic() - start < 1.0
        # connection usable afterwards (fresh reconnect under the hood)
        client.set("k", b"v")
        assert client.get("k") == b"v"
        store.shutdown()

    def test_prefixes_isolate(self):
        store = Store()
        a = StoreClient(store.address(), prefix="quorum_1/0")
        b = StoreClient(store.address(), prefix="quorum_2/0")
        a.set("x", b"one")
        with pytest.raises(TimeoutError):
            b.get("x", timeout=timedelta(milliseconds=50))
        b.set("x", b"two")
        assert a.get("x") == b"one"
        assert b.get("x") == b"two"
        store.shutdown()

    def test_blocking_get_wakes_on_set(self):
        store = Store()
        client_w = StoreClient(store.address())
        client_r = StoreClient(store.address())
        out = {}

        def read():
            out["v"] = client_r.get("later", timeout=timedelta(seconds=10))

        t = threading.Thread(target=read, daemon=True)
        t.start()
        time.sleep(0.1)
        client_w.set("later", b"data")
        t.join(timeout=5)
        assert out["v"] == b"data"
        store.shutdown()


class TestLighthouse:
    # Reference src/lighthouse.rs:910-952 (test_lighthouse_e2e) — the single
    # replica long-poll path, plus the <0.4s join-latency bound from
    # torchft/lighthouse_test.py:44-47.
    def test_single_replica_quorum_latency(self, lighthouse):
        store = Store()
        m = Manager(
            "foo", lighthouse.address(), "localhost", "[::]:0", store.address(), 1
        )
        client = ManagerClient(m.address())
        start = time.monotonic()
        result = client.quorum(0, 10, "md", timeout=TIMEOUT)
        elapsed = time.monotonic() - start
        assert result.quorum_id == 1
        assert result.replica_world_size == 1
        assert result.max_step == 10
        assert elapsed < 0.4, f"quorum took {elapsed:.3f}s"
        m.shutdown()
        store.shutdown()

    def test_force_reconfigure_bumps_quorum_id(self, lighthouse):
        # A member whose data plane failed requests force_reconfigure: the
        # lighthouse must bump quorum_id even though membership is
        # unchanged, so every member rebuilds on a fresh rendezvous prefix.
        store = Store()
        m = Manager(
            "fr", lighthouse.address(), "localhost", "[::]:0", store.address(), 1
        )
        client = ManagerClient(m.address())
        r1 = client.quorum(0, 1, "md", timeout=TIMEOUT)
        r2 = client.quorum(0, 2, "md", timeout=TIMEOUT)
        assert r2.quorum_id == r1.quorum_id  # same membership: stable id
        r3 = client.quorum(0, 3, "md", force_reconfigure=True, timeout=TIMEOUT)
        assert r3.quorum_id == r1.quorum_id + 1
        r4 = client.quorum(0, 4, "md", timeout=TIMEOUT)
        assert r4.quorum_id == r3.quorum_id  # one-shot: flag does not stick
        m.shutdown()
        store.shutdown()

    # Reference src/lighthouse.rs:1036-1140 (test_lighthouse_join_during_shrink).
    def test_join_during_shrink(self):
        lh = Lighthouse(min_replicas=2, join_timeout_ms=1000)
        store = Store()
        managers = {
            name: Manager(
                name, lh.address(), "localhost", "[::]:0", store.address(), 1
            )
            for name in ("replica0", "replica1", "joiner")
        }
        clients = {name: ManagerClient(m.address()) for name, m in managers.items()}

        # 1. first quorum: replica0 + replica1
        first = _quorum_threads(
            [("replica0", clients["replica0"], 1), ("replica1", clients["replica1"], 1)]
        )
        assert first["replica0"].replica_world_size == 2
        q1 = first["replica0"].quorum_id

        # 2. joiner asks to join; replica0 requests shrink_only — joiner must
        # be excluded even though it is heartbeating and participating
        joiner_result = {}

        def join():
            joiner_result["r"] = clients["joiner"].quorum(
                0, 1, "ckpt-joiner", timeout=TIMEOUT
            )

        jt = threading.Thread(target=join, daemon=True)
        jt.start()
        time.sleep(0.2)

        second = _quorum_threads(
            [
                ("replica0", clients["replica0"], 2),
                ("replica1", clients["replica1"], 2),
            ],
            shrink_only={"replica0"},
        )
        assert second["replica0"].replica_world_size == 2
        assert second["replica0"].quorum_id == q1  # same members -> no bump

        # 3. next quorum without shrink_only admits the joiner
        third = _quorum_threads(
            [
                ("replica0", clients["replica0"], 3),
                ("replica1", clients["replica1"], 3),
            ]
        )
        assert third["replica0"].replica_world_size == 3
        assert third["replica0"].quorum_id != q1

        jt.join(timeout=10)
        assert joiner_result["r"].replica_world_size == 3
        assert joiner_result["r"].heal  # behind max_step -> must recover

        for m in managers.values():
            m.shutdown()
        lh.shutdown()
        store.shutdown()

    def test_failover_after_heartbeat_expiry(self):
        lh = Lighthouse(min_replicas=1, join_timeout_ms=100, heartbeat_timeout_ms=400)
        store = Store()
        mA = Manager("repA", lh.address(), "localhost", "[::]:0", store.address(), 1)
        mB = Manager("repB", lh.address(), "localhost", "[::]:0", store.address(), 1)
        cA, cB = ManagerClient(mA.address()), ManagerClient(mB.address())

        both = _quorum_threads([("A", cA, 1), ("B", cB, 1)])
        assert both["A"].replica_world_size == 2
        q1 = both["A"].quorum_id

        mB.shutdown() # heartbeats stop
        time.sleep(0.6) # > heartbeat_timeout_ms

        start = time.monotonic()
        alone = cA.quorum(0, 2, "ckpt-A", timeout=TIMEOUT)
        elapsed = time.monotonic() - start
        assert alone.replica_world_size == 1
        assert alone.quorum_id != q1
        assert elapsed < 2.0, f"failover quorum took {elapsed:.3f}s"

        mA.shutdown()
        lh.shutdown()
        store.shutdown()

    def test_heartbeat_only_participant_blocks_quorum(self, lighthouse):
        # A heartbeating non-participant triggers the split-brain guard.
        lighthouse_heartbeat(lighthouse.address(), "bystander")
        store = Store()
        m = Manager(
            "active", lighthouse.address(), "localhost", "[::]:0", store.address(), 1
        )
        client = ManagerClient(m.address())
        with pytest.raises(TimeoutError):
            client.quorum(0, 1, "md", timeout=timedelta(milliseconds=300))
        m.shutdown()
        store.shutdown()


class TestManager:
    # Reference src/manager.rs:504-556 (test_should_commit).
    def test_should_commit_votes(self, lighthouse):
        store = Store()
        m = Manager(
            "rep", lighthouse.address(), "localhost", "[::]:0", store.address(), 2
        )
        client = ManagerClient(m.address())

        results = {}

        def vote(rank, ok):
            results[rank] = client.should_commit(rank, 0, ok, timeout=TIMEOUT)

        # unanimous yes
        ts = [
            threading.Thread(target=vote, args=(0, True), daemon=True),
            threading.Thread(target=vote, args=(1, True), daemon=True),
        ]
        [t.start() for t in ts]
        [t.join(timeout=10) for t in ts]
        assert results == {0: True, 1: True}

        # one failure vetoes the group
        ts = [
            threading.Thread(target=vote, args=(0, True), daemon=True),
            threading.Thread(target=vote, args=(1, False), daemon=True),
        ]
        [t.start() for t in ts]
        [t.join(timeout=10) for t in ts]
        assert results == {0: False, 1: False}

        m.shutdown()
        store.shutdown()

    # Reference src/manager.rs:606-660 (test_checkpoint_metadata).
    def test_checkpoint_metadata(self, lighthouse):
        store = Store()
        m = Manager(
            "rep", lighthouse.address(), "localhost", "[::]:0", store.address(), 1
        )
        client = ManagerClient(m.address())
        with pytest.raises(RuntimeError, match="rank not found"):
            client.checkpoint_metadata(0, timeout=TIMEOUT)
        client.quorum(0, 0, "the-metadata", timeout=TIMEOUT)
        assert client.checkpoint_metadata(0, timeout=TIMEOUT) == "the-metadata"
        m.shutdown()
        store.shutdown()

    # Fast-fail bound mirroring torchft/manager_integ_test.py:356-368.
    def test_quorum_fast_timeout(self, lighthouse):
        store = Store()
        m = Manager(
            "rep", lighthouse.address(), "localhost", "[::]:0", store.address(), 2
        )
        client = ManagerClient(m.address())
        start = time.monotonic()
        with pytest.raises(TimeoutError):
            # world_size=2 but only one rank joins
            client.quorum(0, 0, "md", timeout=timedelta(milliseconds=10))
        assert time.monotonic() - start < 1.0
        start = time.monotonic()
        with pytest.raises(TimeoutError):
            client.should_commit(0, 0, True, timeout=timedelta(milliseconds=10))
        assert time.monotonic() - start < 1.0
        m.shutdown()
        store.shutdown()

    def test_kill_rpc_exits_process(self, lighthouse, tmp_path):
        store = Store()
        script = f"""
import sys, time
sys.path.insert(0, {sys.path[0]!r})
sys.path.insert(0, {__file__.rsplit("/tests", 1)[0]!r})
from torchft_tpu._native import Manager
m = Manager("victim", {lighthouse.address()!r}, "localhost", "[::]:0",
             {store.address()!r}, world_size=1)
print(m.address(), flush=True)
time.sleep(60)
"""
        child = subprocess.Popen(
            [sys.executable, "-c", script], stdout=subprocess.PIPE, text=True
        )
        try:
            addr = child.stdout.readline().strip()
            assert addr.startswith("http://")
            ManagerClient(addr).kill("test kill")
            assert child.wait(timeout=10) == 1
        finally:
            if child.poll() is None:
                child.kill()
        store.shutdown()


class TestMemberStatusExport:
    """Member-health digests ride lease renewals into the lighthouse's
    /status.json per-member view (the fleet-visible half of the policy
    engine's signal surface)."""

    def test_status_rides_renewals_into_status_json(self, lighthouse):
        store = Store()
        m = Manager(
            "statusrep",
            lighthouse.address(),
            "localhost",
            "[::]:0",
            store.address(),
            1,
            heartbeat_interval=timedelta(milliseconds=50),
        )
        try:
            m.set_status(
                {"churn_per_min": 1.5, "wire_eff_MBps": 42.0, "step": 7}
            )
            deadline = time.monotonic() + 10
            entry = None
            while time.monotonic() < deadline:
                members = lighthouse.status_json()["members"]
                entry = next(
                    (e for e in members if e["replica_id"] == "statusrep"),
                    None,
                )
                if entry is not None and "status" in entry:
                    break
                time.sleep(0.05)
            assert entry is not None and "status" in entry, entry
            # the digest arrives PARSED (an object, not a string blob)
            assert entry["status"]["wire_eff_MBps"] == 42.0
            assert entry["status"]["step"] == 7
        finally:
            m.shutdown()
            store.shutdown()
