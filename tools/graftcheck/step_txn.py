"""Model: per-step AND-vote commit transaction (Manager.should_commit).

Protocol core being modeled (torchft_tpu/manager.py):

- Every member of the current quorum finishes its step work and votes
  ``local_ok`` (False iff an error latched during the step) to a central
  collector (lighthouse client ``should_commit``), tagged with its
  ``(step, quorum_id)``.  The vote value for a given (member, step,
  quorum_id) is immutable: RPC retries resend the same value, and an
  error that strikes after the vote was computed latches for the *next*
  step, not this one.
- The collector AND-reduces votes *for the matching (step, quorum_id)
  round only* and, once every quorum member has voted, answers every
  collected vote with a single commit/abort decision.  A collector
  timeout answers the collected votes with an abort.
- A member applies a decision only if it matches its own
  ``(step, quorum_id)``; commit advances the step, abort retries the
  vote.  A latched member does not retry -- its only path forward is the
  reconfigure.
- A reconfigure bumps ``quorum_id``, heals latched members from the most
  advanced survivor, and strands in-flight messages of the old epoch
  behind the (step, quorum_id) guards.

Fault actions: error latch mid-step (before the vote is computed),
member crash, message drop, message duplication.  All bounded by a
per-fault budget so the sweep terminates.

Properties:

- ``epoch_purity``  -- among *live* members, every committed step
  commits under exactly one quorum_id (no mixed-quorum commit; a member
  that commits and then crashes is excluded -- survivors legitimately
  redo its step under the reformed quorum, and the dead member can only
  come back through a heal that overwrites its state).
- ``silent_commit`` -- the collector never emits (and no member ever
  applies) a commit for a round in which a live quorum member's vote
  for that step was No.

Broken variant ``stale_votes`` removes the collector's (step, quorum_id)
round guard: a duplicated Yes vote from an earlier step can then fill a
later round's tally over a latched member's No vote and commit the step
-- the model finds the interleaving and prints its replay line.
"""

from __future__ import annotations

from .core import Model, bag_remove, tup_bag, tup_set

WORK, VOTED = 0, 1
NO_CAST = -1


class StepTxnModel(Model):
    name = "step_txn"
    properties = ("epoch_purity", "silent_commit")

    def __init__(
        self,
        world: int = 2,
        max_step: int = 2,
        latches: int = 1,
        crashes: int = 1,
        drops: int = 1,
        dups: int = 1,
        stale_votes: bool = False,
    ):
        self.world = world
        self.max_step = max_step
        self.faults0 = (latches, crashes, drops, dups)
        # Broken variant: collector ignores the (step, qid) round guard.
        self.stale_votes = bool(stale_votes)
        if stale_votes:
            self.name = "step_txn_stale_votes"

    def budget(self) -> dict:
        return {"max_depth": 48, "max_states": 600_000}

    # State:
    #   members : tuple[(alive, step, qid, latched, phase, cast)]
    #             cast = the ok this member voted for its current step
    #             (NO_CAST until the first vote; immutable until the
    #             step commits or the quorum reforms)
    #   qmembers: tuple of member ids in the current quorum
    #   qid     : current quorum id
    #   msgs    : multiset of ("vote", i, step, qid, ok)
    #                       | ("decide", i, step, qid, commit)
    #   tally   : None | (step, qid, mask, all_ok)
    #   commits : set of (step, qid, member) applied in the fleet
    #   silent  : 1 if a commit was emitted/applied over a latched No
    #   faults  : (latches, crashes, drops, dups) remaining
    def initial(self):
        members = tuple(
            (1, 0, 1, 0, WORK, NO_CAST) for _ in range(self.world)
        )
        qmembers = tuple(range(self.world))
        return (members, qmembers, 1, (), None, (), 0, self.faults0)

    def check(self, state):
        members, qmembers, qid, msgs, tally, commits, silent, faults = state
        out = []
        steps = {}
        for s, q, i in commits:
            if not members[i][0]:
                continue  # dead committer: survivors may redo its step
            if steps.setdefault(s, q) != q:
                out.append("epoch_purity")
                break
        if silent:
            out.append("silent_commit")
        return out

    def actions(self, state):
        members, qmembers, qid, msgs, tally, commits, silent, faults = state
        latches, crashes, drops, dups = faults
        acts = []

        for i, (alive, step, mqid, latched, phase, cast) in enumerate(members):
            if not alive or step >= self.max_step:
                continue
            if phase == WORK and not (latched and cast != NO_CAST):
                # Finish the step's work and cast the vote.  The value is
                # computed once per (step, qid); retries resend it.
                ok = cast if cast != NO_CAST else (0 if latched else 1)
                vote = ("vote", i, step, mqid, ok)
                nm = _set(members, i, (alive, step, mqid, latched, VOTED, ok))
                acts.append(
                    (
                        "work%d" % i,
                        (nm, qmembers, qid, tup_bag(msgs + (vote,)), tally,
                         commits, silent, faults),
                    )
                )
            if phase == WORK and latches > 0 and not latched and cast == NO_CAST:
                # An error latches mid-step (report_error, never raises),
                # before the vote value is computed.
                nm = _set(members, i, (alive, step, mqid, 1, phase, cast))
                acts.append(
                    (
                        "latch%d" % i,
                        (nm, qmembers, qid, msgs, tally, commits, silent,
                         (latches - 1, crashes, drops, dups)),
                    )
                )
            if phase == VOTED:
                # Member-side deadline: give up waiting, re-send the vote.
                nm = _set(members, i, (alive, step, mqid, latched, WORK, cast))
                acts.append(
                    (
                        "mtimeout%d" % i,
                        (nm, qmembers, qid, msgs, tally, commits, silent,
                         faults),
                    )
                )
            if crashes > 0:
                nm = _set(members, i, (0, step, mqid, latched, phase, cast))
                acts.append(
                    (
                        "crash%d" % i,
                        (nm, qmembers, qid, msgs, tally, commits, silent,
                         (latches, crashes - 1, drops, dups)),
                    )
                )

        for m in sorted(set(msgs)):
            rest = bag_remove(msgs, m)
            if m[0] == "vote":
                _, i, vstep, vqid, ok = m
                nt, out_msgs, emitted_silent = self._collect(
                    members, qmembers, tally, i, vstep, vqid, ok
                )
                acts.append(
                    (
                        "rx_vote%d_s%d_q%d" % (i, vstep, vqid),
                        (members, qmembers, qid, tup_bag(rest + out_msgs), nt,
                         commits, silent or emitted_silent, faults),
                    )
                )
            else:  # decide
                _, i, dstep, dqid, commit = m
                alive, step, mqid, latched, phase, cast = members[i]
                nm, ncommits, nsilent = members, commits, silent
                if alive and phase == VOTED and step == dstep and mqid == dqid:
                    if commit:
                        nm = _set(
                            members, i,
                            (alive, step + 1, mqid, latched, WORK, NO_CAST),
                        )
                        ncommits = tup_set(commits + ((dstep, dqid, i),))
                        if latched:
                            nsilent = 1
                    else:
                        nm = _set(
                            members, i,
                            (alive, step, mqid, latched, WORK, cast),
                        )
                acts.append(
                    (
                        "rx_decide%d_s%d_q%d_c%d" % (i, dstep, dqid, commit),
                        (nm, qmembers, qid, rest, tally, ncommits, nsilent,
                         faults),
                    )
                )
            if drops > 0:
                acts.append(
                    (
                        "drop_%s" % _mkey(m),
                        (members, qmembers, qid, rest, tally, commits, silent,
                         (latches, crashes, drops - 1, dups)),
                    )
                )
            if dups > 0:
                acts.append(
                    (
                        "dup_%s" % _mkey(m),
                        (members, qmembers, qid, tup_bag(msgs + (m,)), tally,
                         commits, silent,
                         (latches, crashes, drops, dups - 1)),
                    )
                )

        # Collector deadline: answer the collected votes with an abort.
        if tally is not None:
            ts, tq, mask, _ok = tally
            aborts = tuple(
                ("decide", j, ts, tq, 0) for j in qmembers if mask & (1 << j)
            )
            acts.append(
                (
                    "timeout_s%d_q%d" % (ts, tq),
                    (members, qmembers, qid, tup_bag(msgs + aborts), None,
                     commits, silent, faults),
                )
            )

        # Reconfigure: quorum reforms around the live members, healing
        # latched members from the most advanced survivor.
        need_reform = any(
            not members[i][0] or members[i][3] for i in qmembers
        )
        alive_ids = tuple(i for i in range(self.world) if members[i][0])
        if need_reform and alive_ids:
            donor_step = max(members[i][1] for i in alive_ids)
            nq = qid + 1
            nm = tuple(
                (a, donor_step if a else st, nq if a else mq, 0 if a else la,
                 WORK if a else ph, NO_CAST if a else ca)
                for (a, st, mq, la, ph, ca) in members
            )
            acts.append(
                (
                    "reform_q%d" % nq,
                    (nm, alive_ids, nq, msgs, None, commits, silent, faults),
                )
            )

        return acts

    def _collect(self, members, qmembers, tally, i, vstep, vqid, ok):
        """Collector AND-reduce; returns (tally', out_msgs, emitted_silent)."""
        if tally is None:
            tally = (vstep, vqid, 0, 1)
        ts, tq, mask, all_ok = tally
        if (vstep, vqid) != (ts, tq) and not self.stale_votes:
            # Stale round: answer it with an abort so the sender retries.
            return tally, (("decide", i, vstep, vqid, 0),), 0
        bit = 1 << i
        if not (mask & bit):
            mask |= bit
            all_ok &= ok
        full = 0
        for j in qmembers:
            full |= 1 << j
        if mask & full == full:
            decides = tuple(("decide", j, ts, tq, all_ok) for j in qmembers)
            # The property: a commit emitted while a live quorum member's
            # vote for this step was No is a silent commit.
            emitted_silent = 0
            if all_ok:
                for j in qmembers:
                    alive, step, mqid, latched, phase, cast = members[j]
                    if alive and latched and step == ts and mqid == tq:
                        emitted_silent = 1
            return None, decides, emitted_silent
        return (ts, tq, mask, all_ok), (), 0


def _set(members, i, v):
    return members[:i] + (v,) + members[i + 1:]


def _mkey(m):
    return "%s%d_s%d_q%d_%d" % (m[0][0], m[1], m[2], m[3], m[4])


def make(broken: str = "") -> Model:
    if broken == "stale_votes":
        return StepTxnModel(stale_votes=True)
    if broken:
        raise ValueError("step_txn: unknown broken variant %r" % broken)
    return StepTxnModel()


BROKEN = ("stale_votes",)
