"""Decoder-only transformer LM: the flagship model for fault-tolerant
training demos and benchmarks.

Pure-functional (pytree params + jax fns), designed TPU-first:

- all matmuls are large, batched and bfloat16 (MXU-shaped; dims multiples
  of 128 at the flagship config),
- static shapes and compiler-friendly control flow only (no data-dependent
  Python branching under jit),
- Megatron-style tensor-parallel sharding rules over a ``model`` mesh axis
  (column-parallel QKV/up-projection, row-parallel out/down-projection),
  expressed as PartitionSpecs — XLA inserts the ICI collectives,
- batch sharded over a ``data`` mesh axis.

The reference has no model zoo (torchft wraps user models, train_ddp.py's
CNN is the only demo); this module is the analog of that demo model plus
the sharding contract the HSDP composition needs
(reference process_group.py:1310-1341 leaves intra-group dims to the user —
here the intra-group sharding is first-class).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32768
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 6
    d_ff: int = 2048
    max_seq_len: int = 1024
    dtype: Any = jnp.bfloat16  # activation/matmul dtype; params stay f32
    # Context parallelism: when set, attention runs as ring attention with
    # the sequence sharded over this mesh axis (torchft_tpu.context_parallel)
    # instead of dense O(S^2) attention. cp_mesh carries the slice mesh into
    # the op (compared by identity, not traced); cp_head_axis names the
    # tensor-parallel axis heads are split over, if any.
    cp_seq_axis: Any = None
    cp_mesh: Any = None
    cp_batch_axis: Any = "data"
    cp_head_axis: Any = None
    # "ring" (k/v ppermute + online softmax) or "ulysses" (head/seq
    # all-to-alls around full-sequence attention — which then runs through
    # the fused pallas kernel when use_flash is set)
    cp_strategy: str = "ring"
    # Fused pallas flash attention (torchft_tpu.ops.flash_attention): no
    # S x S score matrix in HBM. Consumed by (a) the non-CP path — when
    # cp_mesh is set the kernel runs per-shard under shard_map with batch
    # over cp_batch_axis and heads over cp_head_axis — and (b) the
    # cp_strategy="ulysses" path, where each device's full-sequence
    # attention runs through the kernel. Ignored by cp_strategy="ring"
    # (that path fuses its own online-softmax loop).
    use_flash: bool = False
    # Flash-kernel VMEM tile overrides (None = the kernel's v5e-measured
    # auto sizes, ops/flash_attention.py); in-model winners can differ
    # from standalone sweeps (fusion/VMEM interactions), so the bench
    # tunes these against whole-step throughput.
    flash_block_q: Any = None
    flash_block_k: Any = None
    # Sliding-window (local) attention width; requires use_flash (the
    # kernel skips out-of-window tiles). None = full causal attention.
    attn_window: Any = None
    # Rematerialize each block's activations in backward (jax.checkpoint):
    # trades ~1/3 extra FLOPs for O(n_layers) less HBM — the standard TPU
    # recipe for long-sequence / large-batch configs.
    remat: bool = False
    # With remat on, "save_attn" keeps each block's attention output AND
    # the flash kernel's (out, lse) residuals (cheap: O(B*S*D) per layer)
    # so the backward replay prunes the forward flash launch — the
    # standard pairing for the flash kernel under remat. On the dense
    # path it only saves the post-projection output (the softmax
    # internals are still recomputed: its vjp needs them either way).
    # None = full recompute.
    remat_policy: Any = None

    def __post_init__(self):
        if self.cp_strategy not in ("ring", "ulysses"):
            raise ValueError(
                f"cp_strategy must be 'ring' or 'ulysses', got "
                f"{self.cp_strategy!r}"
            )
        if self.remat_policy not in (None, "save_attn"):
            raise ValueError(
                f"remat_policy must be None or 'save_attn', got "
                f"{self.remat_policy!r}"
            )
        if self.attn_window is not None and not self.use_flash:
            raise ValueError(
                "attn_window requires use_flash=True (the dense and ring "
                "paths do not implement sliding windows)"
            )
        if self.attn_window is not None and self.cp_seq_axis is not None:
            raise ValueError(
                "attn_window is not implemented on the context-parallel "
                "paths (ring/ulysses take the attention branch before the "
                "flash kernel); unset cp_seq_axis or attn_window"
            )

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads


def tiny_config() -> TransformerConfig:
    """Small config for tests / virtual-device dry runs."""
    return TransformerConfig(
        vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq_len=128,
    )


def _dense_init(k, shape, s):
    return jax.random.normal(k, shape, jnp.float32) * s


def attn_sublayer_init(
    cfg: TransformerConfig, k_qkv: jax.Array, k_o: jax.Array
) -> Dict[str, Any]:
    """ln1 + attention weights; shared by the dense and MoE families."""
    scale = cfg.d_model ** -0.5
    return {
        "ln1": {"scale": jnp.ones((cfg.d_model,), jnp.float32)},
        "attn": {
            # fused QKV, column-parallel over the model axis
            "wqkv": _dense_init(k_qkv, (cfg.d_model, 3 * cfg.d_model), scale),
            # out projection, row-parallel
            "wo": _dense_init(k_o, (cfg.d_model, cfg.d_model), scale),
        },
        "ln2": {"scale": jnp.ones((cfg.d_model,), jnp.float32)},
    }


def mlp_init(
    cfg: TransformerConfig, k_i: jax.Array, k_o: jax.Array
) -> Dict[str, Any]:
    scale = cfg.d_model ** -0.5
    return {
        "wi": _dense_init(k_i, (cfg.d_model, cfg.d_ff), scale),
        "wo": _dense_init(k_o, (cfg.d_ff, cfg.d_model), cfg.d_ff ** -0.5),
    }


def backbone_init(
    cfg: TransformerConfig, k_embed: jax.Array, k_pos: jax.Array
) -> Dict[str, Any]:
    """embed / pos_embed / ln_f — the non-block params both families
    share."""
    scale = cfg.d_model ** -0.5
    return {
        "embed": jax.random.normal(
            k_embed, (cfg.vocab_size, cfg.d_model), jnp.float32
        ) * scale,
        "pos_embed": jax.random.normal(
            k_pos, (cfg.max_seq_len, cfg.d_model), jnp.float32
        ) * 0.01,
        "ln_f": {"scale": jnp.ones((cfg.d_model,), jnp.float32)},
    }


def backbone_specs() -> Dict[str, Any]:
    return {
        "embed": P(None, "model"),
        "pos_embed": P(),
        "ln_f": {"scale": P()},
    }


def embed_tokens(
    cfg: TransformerConfig, params: Dict[str, Any], tokens: jax.Array
) -> jax.Array:
    """(B, S) int32 -> (B, S, D) activations in cfg.dtype."""
    S = tokens.shape[1]
    x = params["embed"].astype(cfg.dtype)[tokens]
    return x + params["pos_embed"].astype(cfg.dtype)[:S]


def readout(
    cfg: TransformerConfig, params: Dict[str, Any], x: jax.Array
) -> jax.Array:
    """Final norm + weight-tied readout; f32 logits for a stable
    softmax."""
    x = _rmsnorm(x, params["ln_f"]["scale"])
    return (x @ params["embed"].astype(cfg.dtype).T).astype(jnp.float32)


def mlp_apply(
    cfg: TransformerConfig, p: Dict[str, Any], x: jax.Array
) -> jax.Array:
    h = jax.nn.gelu(x @ p["wi"].astype(cfg.dtype))
    return h @ p["wo"].astype(cfg.dtype)


def next_token_loss(logits: jax.Array, targets: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def attn_sublayer_specs() -> Dict[str, Any]:
    """Megatron attention PartitionSpecs; shared with the MoE family."""
    return {
        "ln1": {"scale": P()},
        "attn": {
            "wqkv": P(None, "model"),  # column-parallel: heads split
            "wo": P("model", None),    # row-parallel: partial sums psum'd
        },
        "ln2": {"scale": P()},
    }


def mlp_specs() -> Dict[str, Any]:
    return {"wi": P(None, "model"), "wo": P("model", None)}


def init_params(cfg: TransformerConfig, key: jax.Array) -> Dict[str, Any]:
    """f32 master params; matmuls cast to cfg.dtype at use."""
    keys = jax.random.split(key, 2 + cfg.n_layers)
    blocks = []
    for i in range(cfg.n_layers):
        bk = jax.random.split(keys[2 + i], 4)
        block = attn_sublayer_init(cfg, bk[0], bk[1])
        block["mlp"] = mlp_init(cfg, bk[2], bk[3])
        blocks.append(block)
    params = backbone_init(cfg, keys[0], keys[1])
    params["blocks"] = blocks
    return params


def param_sharding_rules(cfg: TransformerConfig) -> Dict[str, Any]:
    """PartitionSpecs (pytree matching init_params) for a mesh with a
    ``model`` axis: Megatron column/row parallelism. Replicated leaves get
    P() so every spec is explicit."""
    block = attn_sublayer_specs()
    block["mlp"] = mlp_specs()
    rules = backbone_specs()
    rules["blocks"] = [block] * cfg.n_layers
    return rules


def _rmsnorm(x: jax.Array, scale: jax.Array) -> jax.Array:
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + 1e-6) * scale).astype(x.dtype)


def _attention(cfg: TransformerConfig, p: Dict[str, Any], x: jax.Array) -> jax.Array:
    """Returns the attention sublayer output, checkpoint-named "attn_out"
    (identity outside a policy-remat context) so remat_policy="save_attn"
    works for every family that calls this — no per-family re-tagging."""
    from jax.ad_checkpoint import checkpoint_name

    return checkpoint_name(_attention_impl(cfg, p, x), "attn_out")


def _attention_impl(cfg: TransformerConfig, p: Dict[str, Any], x: jax.Array) -> jax.Array:
    B, S, D = x.shape
    qkv = x @ p["wqkv"].astype(cfg.dtype)  # (B, S, 3D)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, S, cfg.n_heads, cfg.head_dim)
    k = k.reshape(B, S, cfg.n_heads, cfg.head_dim)
    v = v.reshape(B, S, cfg.n_heads, cfg.head_dim)

    if cfg.cp_seq_axis is not None:
        # Context parallel: sequence sharded over the slice mesh's seq
        # axis, no S x S materialization. Strategy: k/v ring (ppermute) or
        # Ulysses all-to-alls (full-seq attention per head subset).
        from ..context_parallel import ring_attention, ulysses_attention

        if cfg.cp_strategy == "ulysses":
            out = ulysses_attention(
                q, k, v,
                mesh=cfg.cp_mesh,
                seq_axis=cfg.cp_seq_axis,
                batch_axis=cfg.cp_batch_axis,
                head_axis=cfg.cp_head_axis,
                use_flash=cfg.use_flash,
                block_q=cfg.flash_block_q,
                block_k=cfg.flash_block_k,
            ).reshape(B, S, D)
        else:
            out = ring_attention(
                q, k, v,
                mesh=cfg.cp_mesh,
                seq_axis=cfg.cp_seq_axis,
                batch_axis=cfg.cp_batch_axis,
                head_axis=cfg.cp_head_axis,
            ).reshape(B, S, D)
        return out @ p["wo"].astype(cfg.dtype)

    if cfg.use_flash:
        from ..ops import flash_attention

        out = flash_attention(
            q, k, v,
            mesh=cfg.cp_mesh,
            batch_axis=cfg.cp_batch_axis if cfg.cp_mesh is not None else None,
            head_axis=cfg.cp_head_axis,
            window=cfg.attn_window,
            block_q=cfg.flash_block_q,
            block_k=cfg.flash_block_k,
        ).reshape(B, S, D)
        return out @ p["wo"].astype(cfg.dtype)

    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (cfg.head_dim ** -0.5)
    causal = jnp.tril(jnp.ones((S, S), jnp.bool_))
    scores = jnp.where(causal, scores, jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(cfg.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, S, D)
    return out @ p["wo"].astype(cfg.dtype)


def _block(cfg: TransformerConfig, p: Dict[str, Any], x: jax.Array) -> jax.Array:
    x = x + _attention(cfg, p["attn"], _rmsnorm(x, p["ln1"]["scale"]))
    return x + mlp_apply(cfg, p["mlp"], _rmsnorm(x, p["ln2"]["scale"]))


def remat_wrap(cfg: TransformerConfig, fn, static_argnums=(0,)):
    """Apply cfg's remat settings to a block fn; shared by the dense and
    MoE families so remat_policy means the same thing in both."""
    if not cfg.remat:
        return fn
    if cfg.remat_policy == "save_attn":
        policy = jax.checkpoint_policies.save_only_these_names(
            "attn_out", "flash_out", "flash_lse"
        )
        return jax.checkpoint(fn, static_argnums=static_argnums,
                              policy=policy)
    return jax.checkpoint(fn, static_argnums=static_argnums)


def forward(cfg: TransformerConfig, params: Dict[str, Any], tokens: jax.Array) -> jax.Array:
    """tokens (B, S) int32 -> logits (B, S, vocab) f32."""
    x = embed_tokens(cfg, params, tokens)
    block = remat_wrap(cfg, _block)
    for p in params["blocks"]:
        x = block(cfg, p, x)
    return readout(cfg, params, x)


def loss_fn(cfg: TransformerConfig, params: Dict[str, Any], tokens: jax.Array) -> jax.Array:
    """Next-token cross entropy over (B, S) int32 tokens."""
    logits = forward(cfg, params, tokens[:, :-1])
    return next_token_loss(logits, tokens[:, 1:])


def make_train_step(
    cfg: TransformerConfig, tx: Any, bf16_params: bool = False
) -> Any:
    """ONE-program train step: loss, grad, and optimizer apply fused into
    a single jitted executable with buffer donation.

    Measured on v5e (111M-param big config, B8 S2048): 216 ms/step fused
    vs 235 ms as separate grad and apply programs; a device-side
    ``lax.scan`` over steps gains nothing further, so the win is the
    program-boundary cost, not host dispatch. Use with
    ``LocalSGD.step_applied``-style window accounting — per-step
    cross-group work (the DDP ring) inherently needs the split programs.

    ``bf16_params``: classic mixed precision with a master copy — the
    gradient pass reads a bf16 working copy of the f32 params (one cast
    pass instead of a per-use cast; halves param/embed HBM read traffic
    and the gradient pytree), while the optimizer updates the f32 master,
    which ``params`` remains throughout. Forward numerics are identical
    to the default (the model casts weights to ``cfg.dtype`` at use
    anyway); what changes is gradient ACCUMULATION precision — multi-use
    cotangent sums run in bf16 — the standard mixed-precision trade.

    Returns ``step(params, opt_state, tokens) -> (params, opt_state,
    loss)``.
    """
    import optax

    def one_step(params, opt_state, tokens):
        if bf16_params:
            compute_params = jax.tree_util.tree_map(
                lambda l: l.astype(jnp.bfloat16)
                if l.dtype == jnp.float32 else l,
                params,
            )
            loss, grads = jax.value_and_grad(
                lambda p: loss_fn(cfg, p, tokens)
            )(compute_params)
            # master update in f32 regardless of wire/grad dtype
            grads = jax.tree_util.tree_map(
                lambda g, m: g.astype(m.dtype), grads, params
            )
        else:
            loss, grads = jax.value_and_grad(
                lambda p: loss_fn(cfg, p, tokens)
            )(params)
        updates, new_opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_opt_state, loss

    return jax.jit(one_step, donate_argnums=(0, 1))
