#include "lighthouse.h"

#include <sys/socket.h>

#include <sstream>

#include "log.h"
#include "manager.h"
#include "wire.h"

namespace tft {

using torchft_tpu::ErrorResponse;
using torchft_tpu::Quorum;
using torchft_tpu::QuorumMember;

Lighthouse::Lighthouse(const std::string& bind_addr, const LighthouseOpt& opt)
    : opt_(opt),
      listener_(std::make_unique<Listener>(bind_addr)),
      hostname_(local_hostname()) {
  accept_thread_ = std::thread([this] { accept_loop(); });
  tick_thread_ = std::thread([this] { tick_loop(); });
  LOG_INFO("Lighthouse listening on: " << address());
}

Lighthouse::~Lighthouse() { shutdown(); }

std::string Lighthouse::address() const {
  return "http://" + hostname_ + ":" + std::to_string(listener_->port());
}

uint16_t Lighthouse::port() const { return listener_->port(); }

void Lighthouse::shutdown() {
  {
    // Flag + notify under the cv's mutex so waiters can't miss the wakeup.
    MutexLock lock(mu_);
    if (shutting_down_.exchange(true)) return;
    quorum_cv_.notify_all();
  }
  listener_->close();
  if (accept_thread_.joinable()) accept_thread_.join();
  if (tick_thread_.joinable()) tick_thread_.join();
  conns_.shutdown_all();
}

void Lighthouse::accept_loop() {
  while (!shutting_down_) {
    Socket sock = listener_->accept();
    if (!sock.valid()) return;
    conns_.spawn(std::move(sock), [this](Socket& s) { handle_conn(s); });
  }
}

void Lighthouse::tick_loop() {
  while (!shutting_down_) {
    {
      MutexLock lock(mu_);
      quorum_tick_locked();
    }
    struct timespec ts;
    ts.tv_sec = opt_.quorum_tick_ms / 1000;
    ts.tv_nsec = (opt_.quorum_tick_ms % 1000) * 1000000;
    nanosleep(&ts, nullptr);
  }
}

void Lighthouse::quorum_tick_locked() {
  auto [quorum_met, reason] = quorum_compute(now_ms(), state_, opt_);
  LOG_DEBUG("Next quorum status: " << reason);

  if (!quorum_met.has_value()) return;
  std::vector<QuorumMember>& participants = *quorum_met;

  bool changed = !state_.prev_quorum.has_value();
  if (!changed) {
    std::vector<QuorumMember> prev(state_.prev_quorum->participants().begin(),
                                   state_.prev_quorum->participants().end());
    changed = quorum_changed(participants, prev);
  }
  // A member with a failed data plane needs everyone to rebuild on a fresh
  // rendezvous namespace, which only a quorum_id bump triggers.
  for (const auto& p : participants) {
    if (p.force_reconfigure()) {
      changed = true;
      LOG_INFO("Member " << p.replica_id() << " requested reconfigure");
      break;
    }
  }
  if (changed) {
    state_.quorum_id += 1;
    state_.quorum_formed_ms = now_ms();
    LOG_INFO("Detected quorum change, bumping quorum_id to " << state_.quorum_id);

    // Event log entry: membership + who is healing (step behind max).
    int64_t max_step = -1;
    for (const auto& p : participants) max_step = std::max(max_step, p.step());
    std::ostringstream ev;
    ev << "[" << format_unix_ms(unix_ms()) << "] quorum " << state_.quorum_id
       << ": " << participants.size() << " member"
       << (participants.size() == 1 ? "" : "s");
    std::string healing;
    for (const auto& p : participants) {
      if (p.step() != max_step) {
        if (!healing.empty()) healing += ", ";
        healing += p.replica_id();
      }
    }
    if (!healing.empty())
      ev << "; healing to step " << max_step << ": " << healing;
    state_.events.push_front(ev.str());
    while (state_.events.size() > 20) state_.events.pop_back();
  }

  Quorum quorum;
  quorum.set_quorum_id(state_.quorum_id);
  for (auto& p : participants) *quorum.add_participants() = std::move(p);
  quorum.set_created_ms(unix_ms());

  LOG_INFO("Quorum! id=" << quorum.quorum_id()
                         << " participants=" << quorum.participants_size());

  state_.prev_quorum = quorum;
  state_.participants.clear();
  latest_quorum_ = std::move(quorum);
  quorum_gen_ += 1;
  quorum_cv_.notify_all();
}

void Lighthouse::handle_conn(Socket& sock) {
  try {
    // Sniff: HTTP dashboards start with an ASCII method; protocol frames start
    // with a u32 length whose first byte is 0 for any sane payload size.
    char head[4] = {0};
    size_t n = sock.peek(head, sizeof(head));
    if (n >= 3 && (memcmp(head, "GET", 3) == 0 || memcmp(head, "POS", 3) == 0)) {
      std::string req_head;
      char buf[1024];
      // Read until end of headers.
      while (req_head.find("\r\n\r\n") == std::string::npos) {
        size_t got = sock.peek(buf, sizeof(buf));
        sock.recv_all(buf, got);
        req_head.append(buf, got);
        if (req_head.size() > 64 * 1024) break;
      }
      handle_http(sock, req_head);
      return;
    }

    while (true) {
      auto [type, payload] = recv_frame(sock);
      switch (type) {
        case MsgType::kLighthouseQuorumReq:
          handle_quorum_req(sock, payload);
          break;
        case MsgType::kLighthouseHeartbeatReq: {
          torchft_tpu::LighthouseHeartbeatRequest req;
          req.ParseFromString(payload);
          {
            MutexLock lock(mu_);
            state_.heartbeats[req.replica_id()] = now_ms();
          }
          send_msg(sock, MsgType::kLighthouseHeartbeatResp,
                   torchft_tpu::LighthouseHeartbeatResponse());
          break;
        }
        default:
          send_error(sock, ErrorResponse::INVALID_ARGUMENT,
                     "unexpected message type");
          return;
      }
    }
  } catch (const std::exception&) {
    // peer went away
  }
}

void Lighthouse::handle_quorum_req(Socket& sock, const std::string& payload) {
  torchft_tpu::LighthouseQuorumRequest req;
  if (!req.ParseFromString(payload) || !req.has_requester()) {
    send_error(sock, ErrorResponse::INVALID_ARGUMENT, "missing requester");
    return;
  }
  const QuorumMember& requester = req.requester();
  LOG_INFO("got quorum request for replica " << requester.replica_id());

  int64_t deadline = req.timeout_ms() <= 0 ? -1 : now_ms() + req.timeout_ms();

  UniqueMutexLock lock(mu_);
  // Joining the quorum is an implicit heartbeat.
  state_.heartbeats[requester.replica_id()] = now_ms();
  state_.participants[requester.replica_id()] =
      ParticipantDetails{now_ms(), requester};
  int64_t gen = quorum_gen_;
  // Proactive tick so a now-complete quorum resolves without waiting a tick.
  quorum_tick_locked();

  while (true) {
    // Wait for a quorum newer than our subscription point.
    while (quorum_gen_ == gen && !shutting_down_) {
      if (deadline < 0) {
        quorum_cv_.wait(lock);
      } else {
        int64_t remain = deadline - now_ms();
        if (remain <= 0) {
          lock.unlock();
          send_error(sock, ErrorResponse::DEADLINE_EXCEEDED,
                     "lighthouse quorum timed out");
          return;
        }
        quorum_cv_.wait_for(lock, std::chrono::milliseconds(remain));
      }
    }
    if (shutting_down_) {
      lock.unlock();
      send_error(sock, ErrorResponse::CANCELLED, "lighthouse shutting down");
      return;
    }
    gen = quorum_gen_;
    bool in_quorum = false;
    for (const auto& p : latest_quorum_.participants()) {
      if (p.replica_id() == requester.replica_id()) {
        in_quorum = true;
        break;
      }
    }
    if (in_quorum) {
      torchft_tpu::LighthouseQuorumResponse resp;
      *resp.mutable_quorum() = latest_quorum_;
      lock.unlock();
      send_msg(sock, MsgType::kLighthouseQuorumResp, resp);
      return;
    }
    // A quorum formed without us (e.g. it was computed just before we joined);
    // re-register and keep waiting.
    LOG_INFO("Replica " << requester.replica_id() << " not in quorum, retrying");
    state_.participants[requester.replica_id()] =
        ParticipantDetails{now_ms(), requester};
  }
}

namespace {

const char kIndexHtml[] = R"html(<!DOCTYPE html>
<html>
<head>
<title>torchft_tpu lighthouse</title>
<style>
body { font-family: system-ui, sans-serif; margin: 2em; background: #10141a; color: #e6e6e6; }
h1 { font-size: 1.4em; }
.card { border: 1px solid #2c3442; border-radius: 8px; padding: 0.8em 1.2em; margin: 0.6em 0; background: #161c26; }
.recovering { border-color: #e0912f; }
.muted { color: #8b96a8; font-size: 0.9em; }
button { background: #933; color: #fff; border: none; border-radius: 4px; padding: 0.3em 0.8em; cursor: pointer; }
table { border-collapse: collapse; }
td, th { padding: 0.2em 0.8em; text-align: left; }
</style>
</head>
<body>
<h1>torchft_tpu lighthouse</h1>
<div id="status">loading...</div>
<script>
async function refresh() {
  try {
    const r = await fetch('/status');
    document.getElementById('status').innerHTML = await r.text();
  } catch (e) {}
}
async function kill(id) {
  await fetch('/replica/' + encodeURIComponent(id) + '/kill', {method: 'POST'});
}
refresh();
setInterval(refresh, 1000);
</script>
</body>
</html>
)html";

void http_respond(Socket& sock, int code, const std::string& content_type,
                  const std::string& body) {
  std::ostringstream os;
  const char* reason = code == 200 ? "OK" : (code == 404 ? "Not Found" : "Error");
  os << "HTTP/1.1 " << code << " " << reason << "\r\n"
     << "Content-Type: " << content_type << "\r\n"
     << "Content-Length: " << body.size() << "\r\n"
     << "Connection: close\r\n\r\n"
     << body;
  std::string out = os.str();
  sock.send_all(out.data(), out.size());
}

std::string html_escape(const std::string& s) {
  std::string out;
  for (char c : s) {
    switch (c) {
      case '<': out += "&lt;"; break;
      case '>': out += "&gt;"; break;
      case '&': out += "&amp;"; break;
      case '"': out += "&quot;"; break;
      case '\'': out += "&#39;"; break;
      default: out += c;
    }
  }
  return out;
}

} // namespace

std::string Lighthouse::render_status_locked() {
  auto [_, quorum_status] = quorum_compute(now_ms(), state_, opt_);

  int64_t max_step = -1;
  int64_t num_participants = -1;
  if (state_.prev_quorum.has_value()) {
    num_participants = state_.prev_quorum->participants_size();
    for (const auto& p : state_.prev_quorum->participants())
      max_step = std::max(max_step, p.step());
  }

  std::ostringstream os;
  os << "<div class=card><b>Quorum " << state_.quorum_id << "</b> &mdash; "
     << num_participants << " participants, max step " << max_step;
  if (state_.quorum_formed_ms >= 0) {
    int64_t age_s = (now_ms() - state_.quorum_formed_ms) / 1000;
    os << ", age " << age_s << " s";
  }
  os << "<div class=muted>" << html_escape(quorum_status) << "</div></div>";

  if (state_.prev_quorum.has_value()) {
    for (const auto& p : state_.prev_quorum->participants()) {
      bool recovering = p.step() != max_step;
      os << "<div class='card" << (recovering ? " recovering" : "") << "'><b>"
         << html_escape(p.replica_id()) << "</b>"
         << (recovering ? " <span class=muted>(recovering)</span>" : "")
         << "<table>"
         << "<tr><td>step</td><td>" << p.step() << "</td></tr>"
         << "<tr><td>manager</td><td>" << html_escape(p.address()) << "</td></tr>"
         << "<tr><td>store</td><td>" << html_escape(p.store_address()) << "</td></tr>"
         << "<tr><td>world size</td><td>" << p.world_size() << "</td></tr>"
         << "</table>"
         // replica_id reaches JS only via dataset (never inlined in code),
         // so a hostile id can't escape into script.
         << "<button data-rid=\"" << html_escape(p.replica_id())
         << "\" onclick=\"kill(this.dataset.rid)\">Kill</button></div>";
    }
  }

  os << "<div class=card><b>Heartbeats</b><table>";
  int64_t now = now_ms();
  for (const auto& [replica_id, last] : state_.heartbeats) {
    bool old = now - last >= opt_.heartbeat_timeout_ms;
    os << "<tr><td>" << html_escape(replica_id) << "</td><td"
       << (old ? " style='color:#e0912f'" : "") << ">" << (now - last)
       << " ms ago</td></tr>";
  }
  os << "</table></div>";

  if (!state_.events.empty()) {
    os << "<div class=card><b>Events</b>";
    for (const auto& ev : state_.events)
      os << "<div class=muted>" << html_escape(ev) << "</div>";
    os << "</div>";
  }
  return os.str();
}

void Lighthouse::handle_http(Socket& sock, const std::string& head) {
  std::istringstream is(head);
  std::string method, path;
  is >> method >> path;

  if (method == "GET" && (path == "/" || path.empty())) {
    http_respond(sock, 200, "text/html", kIndexHtml);
  } else if (method == "GET" && path == "/status") {
    std::string body;
    {
      MutexLock lock(mu_);
      body = render_status_locked();
    }
    http_respond(sock, 200, "text/html", body);
  } else if (method == "POST" && path.rfind("/replica/", 0) == 0 &&
             path.size() > 14 && path.compare(path.size() - 5, 5, "/kill") == 0) {
    std::string replica_id = path.substr(9, path.size() - 9 - 5);
    std::string addr;
    {
      MutexLock lock(mu_);
      if (state_.prev_quorum.has_value()) {
        for (const auto& p : state_.prev_quorum->participants()) {
          if (p.replica_id() == replica_id) {
            addr = p.address();
            break;
          }
        }
      }
    }
    if (addr.empty()) {
      http_respond(sock, 404, "text/plain", "failed to find replica");
      return;
    }
    try {
      ManagerClient client(addr, /*connect_timeout_ms=*/10000);
      client.kill("killed from dashboard");
      http_respond(sock, 200, "text/plain", "ok");
    } catch (const std::exception& e) {
      http_respond(sock, 500, "text/plain", e.what());
    }
  } else {
    http_respond(sock, 404, "text/plain", "not found");
  }
}

} // namespace tft
