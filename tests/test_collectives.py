"""Collectives layer tests.

Mirrors the reference's process-group test strategy
(reference torchft/process_group_test.py): multi-rank collectives run as
threads in one process against a real Store, the Dummy fake is exercised
directly, and reconfiguration / peer-death behavior is asserted.
"""

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta

import numpy as np
import pytest

from torchft_tpu._native import Store
from torchft_tpu.collectives import (
    DummyCollectives,
    HostCollectives,
    ReduceOp,
    Work,
)


@pytest.fixture
def store():
    s = Store()
    yield s
    s.shutdown()


def _make_ring(store, world_size, prefix="q0", timeout=timedelta(seconds=10)):
    """Configure world_size HostCollectives concurrently; returns the list."""
    cols = [HostCollectives(timeout=timeout) for _ in range(world_size)]
    addr = f"{store.address()}/{prefix}"
    with ThreadPoolExecutor(max_workers=world_size) as ex:
        futs = [
            ex.submit(cols[r].configure, addr, r, world_size)
            for r in range(world_size)
        ]
        for f in futs:
            f.result()
    return cols


def _run_all(cols, fn):
    """Runs fn(rank, collectives) on every rank concurrently."""
    results = [None] * len(cols)
    errors = []

    def run(r):
        try:
            results[r] = fn(r, cols[r])
        except Exception as e:  # noqa: BLE001
            errors.append((r, e))

    threads = [threading.Thread(target=run, args=(r,)) for r in range(len(cols))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0][1]
    return results


class TestHostCollectives:
    @pytest.mark.parametrize("world_size", [2, 3, 5])
    def test_allreduce_sum(self, store, world_size):
        cols = _make_ring(store, world_size)
        data = [
            np.arange(17, dtype=np.float32) * (r + 1) for r in range(world_size)
        ]
        expect = sum(data)
        results = _run_all(cols, lambda r, c: c.allreduce(data[r]).wait())
        for out in results:
            np.testing.assert_array_equal(out, expect)
        for c in cols:
            c.shutdown()

    def test_allreduce_bitwise_identical_across_ranks(self, store):
        # The determinism oracle: reduction order is identical on every rank
        # (reference manager_integ_test.py:279-282 demands bit-identical
        # state after recovery).
        cols = _make_ring(store, 4)
        rng = np.random.default_rng(0)
        data = [rng.standard_normal(1001).astype(np.float32) for _ in range(4)]
        results = _run_all(cols, lambda r, c: c.allreduce(data[r]).wait())
        for out in results[1:]:
            assert out.tobytes() == results[0].tobytes()
        for c in cols:
            c.shutdown()

    def test_allreduce_avg_and_ops(self, store):
        cols = _make_ring(store, 2)
        data = [np.array([2.0, 8.0], np.float32), np.array([4.0, 2.0], np.float32)]
        avg = _run_all(cols, lambda r, c: c.allreduce(data[r], ReduceOp.AVG).wait())
        np.testing.assert_array_equal(avg[0], [3.0, 5.0])
        mx = _run_all(cols, lambda r, c: c.allreduce(data[r], ReduceOp.MAX).wait())
        np.testing.assert_array_equal(mx[0], [4.0, 8.0])
        mn = _run_all(cols, lambda r, c: c.allreduce(data[r], ReduceOp.MIN).wait())
        np.testing.assert_array_equal(mn[0], [2.0, 2.0])
        prod = _run_all(
            cols, lambda r, c: c.allreduce(data[r], ReduceOp.PRODUCT).wait()
        )
        np.testing.assert_array_equal(prod[0], [8.0, 16.0])
        for c in cols:
            c.shutdown()

    def test_allreduce_pytree_mixed_dtypes(self, store):
        cols = _make_ring(store, 2)
        trees = [
            {
                "w": np.ones((3, 4), np.float32) * (r + 1),
                "b": np.ones(5, np.float64) * (r + 1),
                "n": np.array([r + 1], np.int64),
            }
            for r in range(2)
        ]
        results = _run_all(cols, lambda r, c: c.allreduce(trees[r]).wait())
        for out in results:
            np.testing.assert_array_equal(out["w"], np.ones((3, 4)) * 3)
            np.testing.assert_array_equal(out["b"], np.ones(5) * 3)
            np.testing.assert_array_equal(out["n"], [3])
            assert out["w"].dtype == np.float32
            assert out["b"].dtype == np.float64
            assert out["n"].dtype == np.int64
        for c in cols:
            c.shutdown()

    def test_allreduce_bfloat16_native_wire(self, store):
        # bf16 ships natively (2 bytes on the wire — half the DCN bytes of
        # an f32 upcast); reduction math is f32 per hop, rounded to nearest
        # even back to bf16. These values are bf16-exact, so the sum is too.
        import ml_dtypes

        cols = _make_ring(store, 3)
        data = [
            np.full(7, 0.125 * (r + 1), dtype=ml_dtypes.bfloat16) for r in range(3)
        ]
        results = _run_all(cols, lambda r, c: c.allreduce(data[r]).wait())
        for out in results:
            assert out.dtype == ml_dtypes.bfloat16
            np.testing.assert_array_equal(
                out.astype(np.float32), np.full(7, 0.75, np.float32)
            )
        for c in cols:
            c.shutdown()

    def test_allreduce_bfloat16_rounds_per_hop(self, store):
        # Inexact sums round per ring hop (the documented bf16 tradeoff);
        # results remain bit-identical across ranks.
        import ml_dtypes

        cols = _make_ring(store, 2)
        data = [
            np.full(5, 1.0 + r * 0.00390625, dtype=ml_dtypes.bfloat16)
            for r in range(2)
        ]
        results = _run_all(cols, lambda r, c: c.allreduce(data[r]).wait())
        expected = (
            data[0].astype(np.float32) + data[1].astype(np.float32)
        ).astype(ml_dtypes.bfloat16)
        for out in results:
            assert out.dtype == ml_dtypes.bfloat16
            np.testing.assert_array_equal(out, expected)
        for c in cols:
            c.shutdown()

    def test_allreduce_jax_arrays(self, store):
        import jax.numpy as jnp

        cols = _make_ring(store, 2)
        data = [jnp.arange(6, dtype=jnp.float32) * (r + 1) for r in range(2)]
        results = _run_all(cols, lambda r, c: c.allreduce(data[r]).wait())
        import jax

        for out in results:
            assert isinstance(out, jax.Array)
            np.testing.assert_array_equal(
                np.asarray(out), np.arange(6, dtype=np.float32) * 3
            )
        for c in cols:
            c.shutdown()

    def test_allreduce_pipelined_chunks_match_single_shot(self, store):
        # The overlap pipeline (chunked d2h/ring/h2d) must be bit-identical
        # to the unchunked path and to the analytic expectation.
        import jax.numpy as jnp

        cols = [
            HostCollectives(
                timeout=timedelta(seconds=10),
                pipeline_chunks=4,
                pipeline_min_bytes=0,  # force the pipeline even when tiny
            )
            for _ in range(2)
        ]
        addr = f"{store.address()}/q0"
        with ThreadPoolExecutor(max_workers=2) as ex:
            for f in [
                ex.submit(cols[r].configure, addr, r, 2) for r in range(2)
            ]:
                f.result()
        rng = np.random.default_rng(5)
        base = rng.standard_normal(10_007).astype(np.float32)  # odd size
        data = [
            {"w": jnp.asarray(base * (r + 1)), "b": jnp.asarray(base[:33])}
            for r in range(2)
        ]
        results = _run_all(
            cols, lambda r, c: c.allreduce(data[r], ReduceOp.AVG).wait()
        )
        expect_w = (base * 1 + base * 2) / 2
        for out in results:
            np.testing.assert_array_equal(np.asarray(out["w"]), expect_w)
            np.testing.assert_array_equal(np.asarray(out["b"]), base[:33])
        assert np.asarray(results[0]["w"]).tobytes() == np.asarray(
            results[1]["w"]
        ).tobytes()
        for c in cols:
            c.shutdown()

    def test_mismatched_pipeline_config_fails_fast(self, store):
        # The chunk schedule is part of the wire contract; disagreeing
        # members must error at configure, not silently desync gradients.
        cols = [
            HostCollectives(
                timeout=timedelta(seconds=10),
                connect_timeout=timedelta(seconds=5),  # rank 0's rendezvous
                pipeline_chunks=chunks,                # times out solo
            )
            for chunks in (4, 8)
        ]
        addr = f"{store.address()}/q0"
        with ThreadPoolExecutor(max_workers=2) as ex:
            futs = [
                ex.submit(cols[r].configure, addr, r, 2) for r in range(2)
            ]
            with pytest.raises(RuntimeError, match="pipeline config mismatch"):
                futs[1].result()
        for c in cols:
            c.shutdown()

    def test_allgather(self, store):
        cols = _make_ring(store, 3)
        results = _run_all(
            cols,
            lambda r, c: c.allgather(
                {"x": np.full(4, r, np.float32), "y": np.array([r], np.int64)}
            ).wait(),
        )
        for out in results:
            assert len(out) == 3
            for r, tree in enumerate(out):
                np.testing.assert_array_equal(tree["x"], np.full(4, r))
                np.testing.assert_array_equal(tree["y"], [r])
        for c in cols:
            c.shutdown()

    def test_allreduce_q8_quantized_ring(self, store):
        # wire="q8": int8 chunks + per-chunk scales, dequant-accumulated
        # per hop; bytes constant in world size (round-3 verdict #9).
        # Results must be (a) within int8 quantization error of the exact
        # sum and (b) BIT-IDENTICAL across ranks (phase-2 circulates
        # owner-quantized codes verbatim).
        import jax.numpy as jnp

        cols = _make_ring(store, 3)
        rng = np.random.default_rng(7)
        base = {
            "w": rng.standard_normal((300,)).astype(np.float32),
            "b": rng.standard_normal((5, 7)).astype(np.float32) * 10.0,
        }

        def op(r, c):
            tree = {
                "w": jnp.asarray(base["w"] * (r + 1)),
                "b": jnp.asarray(base["b"] * (r + 1)),
            }
            return c.allreduce(tree, ReduceOp.AVG, wire="q8").wait()

        results = _run_all(cols, op)
        exact = {k: v * (1 + 2 + 3) / 3 for k, v in base.items()}
        for out in results:
            for k in base:
                got = np.asarray(out[k])
                assert got.dtype == np.float32
                # error bound: per-hop requantization at absmax/127 per
                # chunk; 3 ranks -> a few quantization steps of slack
                tol = 6 * np.abs(exact[k]).max() / 127
                np.testing.assert_allclose(got, exact[k], atol=tol)
        for a, b in zip(results[0:1] * 2, results[1:]):
            for k in base:
                np.testing.assert_array_equal(
                    np.asarray(a[k]), np.asarray(b[k])
                )
        # SUM with divisor composes; MIN/MAX must be rejected
        with pytest.raises(ValueError, match="SUM/AVG"):
            cols[0].allreduce(base, ReduceOp.MAX, wire="q8")
        for c in cols:
            c.shutdown()

    def test_allreduce_q8_nonfinite_poisons_all_members(self, store):
        # A NaN/Inf leaf entering the quantized wire must come out NaN on
        # EVERY member: q8_encode ships a NaN scale for a chunk holding any
        # non-finite value (native/src/collectives.cc), because clamping to
        # int8 range would otherwise encode a diverged model as healthy
        # finite codes and hide the blow-up from every peer.
        import jax.numpy as jnp

        cols = _make_ring(store, 3)
        rng = np.random.default_rng(11)
        base = rng.standard_normal(400).astype(np.float32)

        def op(r, c):
            arr = base * (r + 1)
            if r == 0:
                arr = arr.copy()
                arr[7] = np.nan    # lands in ring chunk 0
                arr[250] = np.inf  # lands in a different ring chunk
            return c.allreduce(
                {"w": jnp.asarray(arr)}, ReduceOp.SUM, wire="q8"
            ).wait()

        results = _run_all(cols, op)
        for out in results:
            got = np.asarray(out["w"])
            assert np.isnan(got[7]), "NaN leaf must poison its chunk"
            assert np.isnan(got[250]), "Inf leaf must poison its chunk"
        # poisoned results stay bit-identical across ranks (NaN included)
        for other in results[1:]:
            assert np.asarray(results[0]["w"]).tobytes() == np.asarray(
                other["w"]
            ).tobytes()
        for c in cols:
            c.shutdown()

    def test_op_schedule_pipeline_bit_identical_across_buckets(self, store):
        # The CROSS-BUFFER op-schedule pipeline (bucket i+1's d2h streams
        # while bucket i rides the ring) must be bit-identical to the
        # non-pipelined path for a mixed-dtype tree, and must record the
        # per-bucket phase breakdown in pop_op_stats.
        import jax.numpy as jnp

        import ml_dtypes

        rng = np.random.default_rng(9)
        base_f32 = rng.standard_normal(5003).astype(np.float32)
        # bf16-exact values so the analytic cross-path comparison is exact
        base_bf16 = (rng.integers(-16, 16, 1001) * 0.125).astype(
            ml_dtypes.bfloat16
        )
        base_i32 = rng.integers(-100, 100, 777, dtype=np.int32)

        def tree(r):
            return {
                "w": jnp.asarray(base_f32 * (r + 1)),
                "b": jnp.asarray(base_bf16) * (r + 1),
                "n": jnp.asarray(base_i32 * (r + 1)),
            }

        outs = {}
        for chunks in (1, 4):
            cols = [
                HostCollectives(
                    timeout=timedelta(seconds=10),
                    pipeline_chunks=chunks,
                    pipeline_min_bytes=0,  # force the pipeline even when tiny
                )
                for _ in range(2)
            ]
            addr = f"{store.address()}/sched{chunks}"
            with ThreadPoolExecutor(max_workers=2) as ex:
                for f in [
                    ex.submit(cols[r].configure, addr, r, 2) for r in range(2)
                ]:
                    f.result()
            results = _run_all(cols, lambda r, c: c.allreduce(tree(r)).wait())
            for k in ("w", "b", "n"):
                assert np.asarray(results[0][k]).tobytes() == np.asarray(
                    results[1][k]
                ).tobytes()
            if chunks == 4:
                stats = [
                    st for st in cols[0].pop_op_stats()
                    if st["op"] == "allreduce"
                ]
                assert stats, "device-packed allreduce must record op stats"
                buckets = stats[-1]["buckets"]
                assert len(buckets) == 3  # one per dtype bucket (f32/f64/i32)
                assert stats[-1]["chunks"] == 3 * 4  # every bucket chunked
            outs[chunks] = results[0]
            for c in cols:
                c.shutdown()
        for k in ("w", "b", "n"):
            assert np.asarray(outs[1][k]).tobytes() == np.asarray(
                outs[4][k]
            ).tobytes()

    def test_abort_under_striping_wakes_all_stripes(self, store):
        # Killing a peer mid-op with stripes > 1 must wake EVERY stripe
        # thread (one surfaced error, within seconds, not one timeout per
        # stripe), and the instance must reconfigure cleanly afterward.
        cols = [
            HostCollectives(timeout=timedelta(seconds=30), stripes=4)
            for _ in range(2)
        ]
        addr = f"{store.address()}/striped"
        with ThreadPoolExecutor(max_workers=2) as ex:
            for f in [
                ex.submit(cols[r].configure, addr, r, 2) for r in range(2)
            ]:
                f.result()
        big = np.ones(1 << 20, np.float32)  # 4 MB -> 4 effective stripes
        w = cols[0].allreduce(big.copy())
        threading.Timer(0.3, cols[1].shutdown).start()  # peer dies mid-op
        start = time.monotonic()
        with pytest.raises(RuntimeError):
            w.wait(timeout=timedelta(seconds=20))
        elapsed = time.monotonic() - start
        assert elapsed < 5.0, (
            f"striped abort took {elapsed:.1f}s — a stripe thread sat out "
            "its own timeout instead of being woken"
        )
        # A fresh configure against a new partner restores service, and the
        # op after it runs all 4 stripes (per-stripe timings prove it).
        fresh = HostCollectives(timeout=timedelta(seconds=30), stripes=4)
        addr2 = f"{store.address()}/striped2"
        with ThreadPoolExecutor(max_workers=2) as ex:
            futs = [
                ex.submit(cols[0].configure, addr2, 0, 2),
                ex.submit(fresh.configure, addr2, 1, 2),
            ]
            for f in futs:
                f.result()
        pair = [cols[0], fresh]
        outs = _run_all(
            pair,
            lambda r, c: c.allreduce(np.ones(1 << 18, np.float32)).wait(),
        )
        for o in outs:
            np.testing.assert_array_equal(o, np.full(1 << 18, 2.0))
        assert len(cols[0]._last_stripe_seconds()) == 4
        for c in pair:
            c.shutdown()

    def test_allgather_device_packed_jax_leaves(self, store):
        # All-jax-leaf trees take the device-packed path (one transfer per
        # exact dtype, byte-preserving): without it a quantized {q, scale}
        # payload costs one device round-trip PER LEAF — measured 3.5 s/op
        # on the tunneled TPU. int8 must NOT be upcast on the wire.
        import jax.numpy as jnp

        cols = _make_ring(store, 3)

        def op(r, c):
            payload = {
                "q": {
                    "a": jnp.full((6,), r - 1, jnp.int8),
                    "b": jnp.full((2, 3), 2 * r, jnp.int8),
                },
                "scale": {
                    "a": jnp.float32(0.5 + r),
                    "b": jnp.float32(1.5 * r),
                },
                "extra_bf16": jnp.full((4,), r, jnp.bfloat16),
            }
            return c.allgather(payload).wait()

        results = _run_all(cols, op)
        for out in results:
            assert len(out) == 3
            for r, tree in enumerate(out):
                assert tree["q"]["a"].dtype == jnp.int8
                np.testing.assert_array_equal(
                    np.asarray(tree["q"]["a"]), np.full((6,), r - 1)
                )
                np.testing.assert_array_equal(
                    np.asarray(tree["q"]["b"]), np.full((2, 3), 2 * r)
                )
                np.testing.assert_allclose(
                    float(tree["scale"]["a"]), 0.5 + r
                )
                np.testing.assert_allclose(
                    float(tree["scale"]["b"]), 1.5 * r
                )
                assert tree["extra_bf16"].dtype == jnp.bfloat16
                np.testing.assert_array_equal(
                    np.asarray(tree["extra_bf16"].astype(jnp.float32)),
                    np.full((4,), r, np.float32),
                )
        for c in cols:
            c.shutdown()

    def test_broadcast(self, store):
        cols = _make_ring(store, 3)
        data = [np.full(8, r, np.float32) for r in range(3)]
        results = _run_all(cols, lambda r, c: c.broadcast(data[r], root=1).wait())
        for out in results:
            np.testing.assert_array_equal(out, np.full(8, 1.0))
        for c in cols:
            c.shutdown()

    def test_barrier(self, store):
        cols = _make_ring(store, 3)
        results = _run_all(cols, lambda r, c: c.barrier().wait())
        assert results == [None, None, None]
        for c in cols:
            c.shutdown()

    def test_world_size_one_is_local(self):
        c = HostCollectives()
        c.configure("ignored:0/q", 0, 1)
        out = c.allreduce(np.arange(3, dtype=np.float32)).wait()
        np.testing.assert_array_equal(out, np.arange(3))
        assert c.allgather(np.ones(2))._future.result() is not None
        c.shutdown()

    def test_reconfigure_to_new_membership(self, store):
        # Quorum change: 3 ranks -> 2 ranks under a new prefix (the
        # per-quorum namespacing of reference manager.py:470-477).
        cols = _make_ring(store, 3, prefix="q1")
        results = _run_all(
            cols, lambda r, c: c.allreduce(np.ones(4, np.float32)).wait()
        )
        np.testing.assert_array_equal(results[0], np.full(4, 3.0))

        survivors = cols[:2]
        addr = f"{store.address()}/q2"
        _run_all(survivors, lambda r, c: c.configure(addr, r, 2))
        results = _run_all(
            survivors, lambda r, c: c.allreduce(np.ones(4, np.float32)).wait()
        )
        np.testing.assert_array_equal(results[0], np.full(4, 2.0))
        for c in cols:
            c.shutdown()

    def test_peer_death_unblocks_with_error(self, store):
        # A dead peer must surface as an error on survivors, not a hang —
        # the property the reference's Baby-process isolation provides
        # (reference process_group.py:303-307).
        cols = _make_ring(store, 2, timeout=timedelta(seconds=30))
        cols[1].shutdown()  # rank 1 dies
        with pytest.raises(RuntimeError):
            cols[0].allreduce(np.ones(1024, np.float32)).wait()
        cols[0].shutdown()

    def test_ring_failure_propagates_to_all_members(self, store):
        # One member's death must fail EVERY member's in-flight op within
        # milliseconds (each failing member shuts its ring sockets down,
        # sweeping EOF around the ring) — not just its direct neighbors.
        # Otherwise non-adjacent members block on the full op timeout and a
        # majority of survivors can never reach the next quorum to heal.
        cols = _make_ring(store, 4, timeout=timedelta(seconds=30))
        big = np.ones(1 << 20, np.float32)
        works = [cols[r].allreduce(big.copy()) for r in range(3)]
        threading.Timer(0.3, cols[3].shutdown).start()  # rank 3 dies mid-op
        start = time.monotonic()
        for w in works:
            with pytest.raises(RuntimeError):
                w.wait(timeout=timedelta(seconds=20))
        elapsed = time.monotonic() - start
        assert elapsed < 5.0, f"failure took {elapsed:.1f}s to propagate"
        # The ring is down until reconfigured: ops fail fast, no hang.
        with pytest.raises(RuntimeError):
            cols[0].allreduce(np.ones(4, np.float32)).wait()
        # A fresh configure (new prefix, as a new quorum provides) restores
        # service for the survivors.
        addr = f"{store.address()}/q_rebuilt"
        with ThreadPoolExecutor(max_workers=3) as ex:
            futs = [
                ex.submit(cols[r].configure, addr, r, 3) for r in range(3)
            ]
            for f in futs:
                f.result()
        out = _run_all(
            cols[:3], lambda r, c: c.allreduce(np.ones(8, np.float32)).wait()
        )
        for o in out:
            np.testing.assert_array_equal(o, np.full(8, 3.0))
        for c in cols[:3]:
            c.shutdown()

    def test_abort_unblocks_inflight_op(self, store):
        cols = _make_ring(store, 2, timeout=timedelta(seconds=30))
        # rank 1 never participates; rank 0's allreduce blocks until abort.
        w = cols[0].allreduce(np.ones(4, np.float32))
        threading.Timer(0.2, cols[0].abort).start()
        with pytest.raises(RuntimeError):
            w.wait(timeout=timedelta(seconds=10))
        for c in cols:
            c.shutdown()

    def test_op_timeout(self, store):
        cols = _make_ring(store, 2, timeout=timedelta(milliseconds=200))
        # rank 1 never joins the op: rank 0 times out.
        with pytest.raises(TimeoutError):
            cols[0].allreduce(np.ones(4, np.float32)).wait()
        for c in cols:
            c.shutdown()

    def test_ops_execute_in_submission_order(self, store):
        cols = _make_ring(store, 2)
        works = [[], []]

        def submit(r, c):
            for i in range(5):
                works[r].append(c.allreduce(np.full(3, float(i), np.float32)))
            return [w.wait() for w in works[r]]

        results = _run_all(cols, submit)
        for r in range(2):
            for i, out in enumerate(results[r]):
                np.testing.assert_array_equal(out, np.full(3, 2.0 * i))
        for c in cols:
            c.shutdown()


class TestWork:
    def test_then_chains_and_propagates_errors(self):
        d = DummyCollectives()
        w = d.allreduce(np.ones(2)).then(lambda t: t * 2)
        np.testing.assert_array_equal(w.wait(), np.full(2, 2.0))

        from concurrent.futures import Future

        f = Future()
        f.set_exception(ValueError("boom"))
        w2 = Work(f).then(lambda t: t)
        assert isinstance(w2.exception(), ValueError)


class TestDummyCollectives:
    def test_semantics(self):
        d = DummyCollectives(rank=1, world_size=3)
        assert d.size() == 3 and d.rank() == 1
        t = {"a": np.ones(2)}
        out = d.allreduce(t).wait()
        np.testing.assert_array_equal(out["a"], t["a"])
        assert len(d.allgather(t).wait()) == 3
        d.configure("x:0/p", 0, 2)
        assert d.configure_count == 1 and d.size() == 2


class TestOpMismatchDetection:
    """Size/dtype-mismatched collective ops must error immediately, not
    deadlock with the smaller member done and the larger one blocked on a
    full kernel buffer (the failure mode behind the bench's wedged diloco
    sync: a 6-layer tree reduced against a 2-layer zeros tree)."""

    def test_mismatched_sizes_error_fast(self, store):
        cols = _make_ring(store, 2, prefix="mismatch")
        with ThreadPoolExecutor(max_workers=2) as ex:
            f0 = ex.submit(
                lambda: cols[0].allreduce(np.ones(1 << 20, np.float32)).wait()
            )
            f1 = ex.submit(
                lambda: cols[1].allreduce(np.ones(1 << 10, np.float32)).wait()
            )
            start = time.monotonic()
            for f in (f0, f1):
                with pytest.raises(RuntimeError, match="mismatch|desync|ring"):
                    f.result(timeout=15)
            assert time.monotonic() - start < 10
        for c in cols:
            c.shutdown()

    def test_mismatched_dtype_error_fast(self, store):
        import jax.numpy as jnp

        cols = _make_ring(store, 2, prefix="mismatch_dt")
        with ThreadPoolExecutor(max_workers=2) as ex:
            f0 = ex.submit(
                lambda: cols[0].allreduce(np.ones(256, np.float32)).wait()
            )
            f1 = ex.submit(
                lambda: cols[1]
                .allreduce(jnp.ones(256, jnp.bfloat16))
                .wait()
            )
            for f in (f0, f1):
                with pytest.raises(RuntimeError, match="mismatch|desync|ring"):
                    f.result(timeout=15)
        for c in cols:
            c.shutdown()


class TestShardedCollectives:
    """First-class reduce_scatter / allgather_into: the decomposed pair
    must be bit-identical to the fused allreduce (the determinism oracle
    extended to the sharded-weight-update schedule), the shard layout must
    tile the payload exactly, and abort must wake every stripe thread."""

    def _make_ring(self, store, world_size, prefix, stripes=1):
        cols = [
            HostCollectives(timeout=timedelta(seconds=15), stripes=stripes)
            for _ in range(world_size)
        ]
        addr = f"{store.address()}/{prefix}"
        with ThreadPoolExecutor(max_workers=world_size) as ex:
            for f in [
                ex.submit(cols[r].configure, addr, r, world_size)
                for r in range(world_size)
            ]:
                f.result()
        return cols

    def _trees(self, world_size, dtype=np.float32):
        # Uneven leaf sizes: the flat count is NOT divisible by 2, 3, or 5
        # (ring chunks and stripe sub-ranges both land on uneven
        # boundaries, exercising the near-equal-chunk padding arithmetic).
        rng = np.random.RandomState(7)
        base = {
            "a": rng.randn(4099).astype(dtype),
            "b": rng.randn(13, 7).astype(dtype),
        }
        return [
            {k: (v * (r + 1)).copy() for k, v in base.items()}
            for r in range(world_size)
        ]

    @pytest.mark.parametrize("world_size", [2, 3, 5])
    @pytest.mark.parametrize("stripes", [1, 4])
    def test_bit_identical_to_fused_f32(self, store, world_size, stripes):
        cols = self._make_ring(
            store, world_size, f"shf32_{world_size}_{stripes}", stripes
        )
        trees = self._trees(world_size)
        fused = _run_all(
            cols, lambda r, c: c.allreduce(trees[r], ReduceOp.SUM).wait()
        )

        def decomposed(r, c):
            sh = c.reduce_scatter(trees[r], ReduceOp.SUM).wait()
            return c.allgather_into(sh).wait()

        dec = _run_all(cols, decomposed)
        for f, d in zip(fused, dec):
            for k in f:
                np.testing.assert_array_equal(np.asarray(f[k]), np.asarray(d[k]))
        for c in cols:
            c.shutdown()

    @pytest.mark.parametrize("stripes", [1, 4])
    def test_bit_identical_to_fused_bf16(self, store, stripes):
        import ml_dtypes

        bf16 = np.dtype(ml_dtypes.bfloat16)
        cols = self._make_ring(store, 3, f"shbf_{stripes}", stripes)
        trees = self._trees(3, dtype=bf16)
        fused = _run_all(
            cols, lambda r, c: c.allreduce(trees[r], ReduceOp.SUM).wait()
        )

        def decomposed(r, c):
            sh = c.reduce_scatter(trees[r], ReduceOp.SUM).wait()
            return c.allgather_into(sh).wait()

        dec = _run_all(cols, decomposed)
        for f, d in zip(fused, dec):
            for k in f:
                np.testing.assert_array_equal(
                    np.asarray(f[k]).view(np.uint16),
                    np.asarray(d[k]).view(np.uint16),
                )
        for c in cols:
            c.shutdown()

    @pytest.mark.parametrize("world_size", [2, 3])
    @pytest.mark.parametrize("stripes", [1, 4])
    def test_bit_identical_to_fused_q8(self, store, world_size, stripes):
        # grid_shard=True replays the fused op's phase-2 owner
        # quantize+decode on the owned shard, so RS+AG must reproduce the
        # fused q8 allreduce bit-for-bit, stripes or not.
        cols = self._make_ring(
            store, world_size, f"shq8_{world_size}_{stripes}", stripes
        )
        trees = self._trees(world_size)
        fused = _run_all(
            cols,
            lambda r, c: c.allreduce(trees[r], ReduceOp.SUM, wire="q8").wait(),
        )

        def decomposed(r, c):
            sh = c.reduce_scatter(
                trees[r], ReduceOp.SUM, wire="q8", grid_shard=True
            ).wait()
            return c.allgather_into(sh).wait()

        dec = _run_all(cols, decomposed)
        for f, d in zip(fused, dec):
            for k in f:
                np.testing.assert_array_equal(np.asarray(f[k]), np.asarray(d[k]))
        for c in cols:
            c.shutdown()

    def test_reduce_scatter_q8_nonfinite_poisons_shard(self, store):
        # The split-op mirror of the fused q8 poisoning contract
        # (ADVICE #4): a NaN/Inf leaf entering the quantized
        # reduce-scatter wire must poison the reduced shard on every
        # member — q8_encode ships a NaN scale for any non-finite chunk,
        # and clamping instead would hide a diverged model behind
        # healthy-looking int8 codes. Wire-crossing chunks decode to NaN
        # (NaN scale); the POISONING member's own chunk keeps its raw
        # Inf/NaN — it accumulates in f32 and never re-rides the lossy
        # wire. Either way the divergence must surface as non-finite.
        cols = self._make_ring(store, 3, "q8poison")
        rng = np.random.default_rng(13)
        base = rng.standard_normal(600).astype(np.float32)

        def op(r, c):
            arr = base * (r + 1)
            if r == 1:
                arr = arr.copy()
                arr[5] = np.nan
                arr[400] = np.inf
            return c.reduce_scatter(
                {"w": arr}, ReduceOp.SUM, wire="q8"
            ).wait()

        shards = _run_all(cols, op)
        poisoned = [False] * 3
        for r, sh in enumerate(shards):
            name = next(iter(sh.values))
            got = np.asarray(sh.values[name])
            # reassemble this rank's global positions and check the ones
            # covering the poisoned elements
            for (start, ln), off in zip(
                sh.ranges[name],
                np.cumsum([0] + [l for _, l in sh.ranges[name]][:-1]),
            ):
                seg = got[off:off + ln]
                for idx in (5, 400):
                    if start <= idx < start + ln:
                        assert not np.isfinite(seg[idx - start]), (
                            f"rank {r}: poisoned element {idx} decoded "
                            "finite from the q8 reduce-scatter wire"
                        )
                        poisoned[r] = True
        assert any(poisoned), "test bug: no shard covered a poisoned index"
        for c in cols:
            c.shutdown()

    def test_ungridded_q8_shard_beats_fused_loss(self, store):
        # Production mode (grid_shard=False): the owned shard skips the
        # lossy phase-2 quantization entirely, so its values must match
        # the EXACT f32 reduction — strictly better than the fused op.
        cols = self._make_ring(store, 2, "shq8exact")
        trees = self._trees(2)
        exact = _run_all(
            cols, lambda r, c: c.allreduce(trees[r], ReduceOp.SUM).wait()
        )

        def rs(r, c):
            return c.reduce_scatter(trees[r], ReduceOp.SUM, wire="q8").wait()

        shards = _run_all(cols, rs)
        for r, sh in enumerate(shards):
            name = next(iter(sh.values))
            flat_exact = np.concatenate(
                [np.asarray(exact[r][k]).ravel() for k in ("a", "b")]
            )
            got = np.asarray(sh.values[name])
            want = np.concatenate(
                [flat_exact[s: s + l] for s, l in sh.ranges[name]]
            )
            # q8 wire is lossy in transit (per-hop requant of partials) but
            # the owned chunk accumulates in f32: error stays at the int8
            # class of each chunk, far under 1% of the dynamic range here
            np.testing.assert_allclose(got, want, rtol=0.05, atol=0.05)
        for c in cols:
            c.shutdown()

    @pytest.mark.parametrize("world_size", [2, 3, 5])
    @pytest.mark.parametrize("stripes", [1, 4])
    def test_shard_ranges_tile_payload(self, store, world_size, stripes):
        # The per-rank owned ranges must partition [0, count) exactly:
        # disjoint, complete, and consistent across uneven world sizes and
        # stripe counts (the padding arithmetic of near-equal chunks).
        cols = self._make_ring(
            store, world_size, f"tile_{world_size}_{stripes}", stripes
        )
        count, esize = 4099 + 13 * 7, 4
        from torchft_tpu.collectives import _effective_stripes

        eff = _effective_stripes(count * esize, stripes)
        cover = np.zeros(count, np.int32)
        for r in range(world_size):
            for s, ln in cols[r]._shard_ranges(count, esize, eff):
                cover[s: s + ln] += 1
        np.testing.assert_array_equal(cover, np.ones(count, np.int32))
        for c in cols:
            c.shutdown()

    def test_bf16_param_wire_bit_identical_across_ranks(self, store):
        # The sharded outer sync's parameter leg: f32 shards allgathered
        # over a bf16 wire. Every member (shard owners included) must end
        # with the identical decoded bf16 words.
        cols = self._make_ring(store, 3, "bfwire", stripes=2)
        trees = self._trees(3)

        def sync(r, c):
            sh = c.reduce_scatter(trees[r], ReduceOp.AVG).wait()
            return c.allgather_into(sh, wire="bf16").wait()

        outs = _run_all(cols, sync)
        for o in outs[1:]:
            for k in o:
                np.testing.assert_array_equal(
                    np.asarray(outs[0][k]), np.asarray(o[k])
                )
        # and the values are the bf16 rounding of the exact average
        import ml_dtypes

        exact = _run_all(
            cols, lambda r, c: c.allreduce(trees[r], ReduceOp.AVG).wait()
        )
        for k in exact[0]:
            want = (
                np.asarray(exact[0][k])
                .astype(ml_dtypes.bfloat16)
                .astype(np.float32)
            )
            np.testing.assert_allclose(
                np.asarray(outs[0][k]), want, rtol=1e-6, atol=1e-6
            )
        for c in cols:
            c.shutdown()

    def test_world_size_one_roundtrip(self):
        col = HostCollectives()
        col.configure("ignored", 0, 1)
        tree = {"w": np.arange(10, dtype=np.float32)}
        sh = col.reduce_scatter(tree, ReduceOp.AVG).wait()
        name = next(iter(sh.values))
        assert sh.counts[name] == 10 and sh.ranges[name] == [(0, 10)]
        out = col.allgather_into(sh).wait()
        np.testing.assert_array_equal(out["w"], tree["w"])
        col.shutdown()

    def test_abort_under_reduce_scatter_wakes_all_stripes(self, store):
        # Mirror of test_abort_under_striping_wakes_all_stripes for the
        # split op: peer death mid-reduce-scatter must wake every stripe
        # thread promptly, and a fresh configure restores service.
        cols = [
            HostCollectives(timeout=timedelta(seconds=30), stripes=4)
            for _ in range(2)
        ]
        addr = f"{store.address()}/rs_striped"
        with ThreadPoolExecutor(max_workers=2) as ex:
            for f in [
                ex.submit(cols[r].configure, addr, r, 2) for r in range(2)
            ]:
                f.result()
        big = {"g": np.ones(1 << 20, np.float32)}  # 4 MB -> 4 stripes
        w = cols[0].reduce_scatter(big)
        threading.Timer(0.3, cols[1].shutdown).start()
        start = time.monotonic()
        with pytest.raises(RuntimeError):
            w.wait(timeout=timedelta(seconds=20))
        elapsed = time.monotonic() - start
        assert elapsed < 5.0, (
            f"striped reduce_scatter abort took {elapsed:.1f}s — a stripe "
            "thread sat out its own timeout instead of being woken"
        )
        fresh = HostCollectives(timeout=timedelta(seconds=30), stripes=4)
        addr2 = f"{store.address()}/rs_striped2"
        with ThreadPoolExecutor(max_workers=2) as ex:
            for f in [
                ex.submit(cols[0].configure, addr2, 0, 2),
                ex.submit(fresh.configure, addr2, 1, 2),
            ]:
                f.result()
        pair = [cols[0], fresh]

        def roundtrip(r, c):
            sh = c.reduce_scatter({"g": np.ones(1 << 18, np.float32)}).wait()
            return c.allgather_into(sh).wait()

        outs = _run_all(pair, roundtrip)
        for o in outs:
            np.testing.assert_array_equal(o["g"], np.full(1 << 18, 2.0))
        for c in pair:
            c.shutdown()

    def test_dummy_roundtrip(self):
        d = DummyCollectives()
        tree = {"w": np.arange(6, dtype=np.float32)}
        sh = d.reduce_scatter(tree, ReduceOp.SUM, divisor=2.0).wait()
        out = d.allgather_into(sh).wait()
        np.testing.assert_allclose(out["w"], tree["w"] / 2.0)
