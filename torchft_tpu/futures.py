"""Timeout layer for async work.

Plays the role of reference torchft/futures.py: a hung collective must fail
the step, never hang it (the wrap happens in ``Manager.wrap_work``, mirroring
reference manager.py:326-363). Timers fire on daemon threads; completion
cancels the timer, and whichever of {result, timeout} lands first wins the
output future (the loser is ignored).
"""

from __future__ import annotations

import threading
from concurrent.futures import Future, InvalidStateError
from datetime import timedelta
from typing import Any, Optional

from .collectives import Work


def future_timeout(fut: "Future[Any]", timeout: timedelta) -> "Future[Any]":
    """Returns a future that mirrors ``fut`` but fails with ``TimeoutError``
    if ``fut`` has not completed within ``timeout``."""
    out: "Future[Any]" = Future()

    def on_timeout() -> None:
        try:
            out.set_exception(
                TimeoutError(f"future did not complete within {timeout}")
            )
        except InvalidStateError:
            pass  # completed first

    timer = threading.Timer(timeout.total_seconds(), on_timeout)
    timer.daemon = True
    timer.start()

    def on_done(f: "Future[Any]") -> None:
        timer.cancel()
        try:
            exc = f.exception()
            if exc is not None:
                out.set_exception(exc)
            else:
                out.set_result(f.result())
        except InvalidStateError:
            pass  # timed out first

    fut.add_done_callback(on_done)
    return out


def work_timeout(work: Work, timeout: timedelta) -> Work:
    """:func:`future_timeout` lifted to :class:`Work`."""
    return Work(future_timeout(work._future, timeout))


def future_wait(fut: "Future[Any]", timeout: Optional[timedelta] = None) -> Any:
    """Blocks for the result, raising ``TimeoutError`` past ``timeout``."""
    return fut.result(
        timeout=timeout.total_seconds() if timeout is not None else None
    )
