"""MoE model family: routing numerics, EP sharding, FT-stack composition."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchft_tpu.models import moe
from torchft_tpu.models.moe import MoEConfig, tiny_moe_config


def _tokens(cfg, batch=2, seq=33, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(
        rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32
    )


def test_forward_shapes_and_finite():
    cfg = tiny_moe_config()
    params = moe.init_params(cfg, jax.random.PRNGKey(0))
    tokens = _tokens(cfg)
    logits, aux = moe.forward(cfg, params, tokens)
    assert logits.shape == (2, 33, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()
    # Switch aux loss is >= 1 at the uniform router and ~E when collapsed
    assert 0.5 < float(aux) < cfg.n_experts + 1


def test_grads_flow_to_all_experts_and_router():
    cfg = tiny_moe_config()
    params = moe.init_params(cfg, jax.random.PRNGKey(0))
    tokens = _tokens(cfg, batch=4, seq=65)
    grads = jax.grad(lambda p: moe.loss_fn(cfg, p, tokens))(params)
    g = grads["blocks"][1]["moe"]
    assert float(jnp.abs(g["router"]).sum()) > 0
    # with capacity 1.25 * 2 * N / 4 every expert should see tokens
    per_expert = jnp.abs(g["wi"]).sum(axis=(1, 2))
    assert (np.asarray(per_expert) > 0).all(), per_expert


def test_single_expert_matches_dense_mlp():
    # E=1, k=1, capacity = all tokens: routing is the identity, so the MoE
    # block must equal a plain MLP with the same weights
    cfg = dataclasses.replace(
        tiny_moe_config(), n_experts=1, router_k=1, capacity_factor=1e9,
        moe_every_block=True, n_layers=1,
    )
    params = moe.init_params(cfg, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, cfg.d_model))
    p = params["blocks"][0]["moe"]
    out, _aux = moe.moe_layer(cfg, p, x.astype(cfg.dtype))
    ref = jax.nn.gelu(
        x.astype(cfg.dtype) @ p["wi"][0].astype(cfg.dtype)
    ) @ p["wo"][0].astype(cfg.dtype)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=2e-2, rtol=2e-2,
    )


def test_capacity_drops_overflow_tokens():
    # capacity 1 slot/expert: combine weights of dropped claims are zero,
    # so each expert contributes to at most 1 token per k
    cfg = dataclasses.replace(
        tiny_moe_config(), capacity_factor=1e-9, n_layers=1,
        moe_every_block=True,
    )
    params = moe.init_params(cfg, jax.random.PRNGKey(0))
    x = jax.random.normal(
        jax.random.PRNGKey(1), (1, 32, cfg.d_model)
    ).astype(cfg.dtype)
    out, _ = moe.moe_layer(cfg, params["blocks"][0]["moe"], x)
    assert np.isfinite(np.asarray(out, np.float32)).all()
    # most tokens got fully dropped -> exact zero rows
    zero_rows = (np.abs(np.asarray(out, np.float32)).sum(-1) == 0).sum()
    assert zero_rows >= 32 - 2 * cfg.n_experts


def test_ep_sharded_matches_unsharded():
    from torchft_tpu.parallel import make_mesh, shard_pytree

    cfg = tiny_moe_config()
    params = moe.init_params(cfg, jax.random.PRNGKey(0))
    tokens = _tokens(cfg, batch=4, seq=33)
    base = moe.loss_fn(cfg, params, tokens)

    mesh = make_mesh({"data": 2, "expert": 2, "model": 2})
    cfg_sh = dataclasses.replace(cfg, cp_mesh=mesh)
    rules = moe.param_sharding_rules(cfg_sh)
    sharded_params = shard_pytree(params, rules, mesh)
    sharded = jax.jit(
        lambda p, t: moe.loss_fn(cfg_sh, p, t)
    )(sharded_params, tokens)
    # Sharding changes the reduction order (per-device partial sums over
    # the expert/model axes) and the model computes in bf16, so the two
    # losses agree to bf16-class accuracy, not f32: observed relative
    # drift ~7e-4 on CPU. 3e-3 keeps ~4x headroom while still catching a
    # routing/sharding bug (those diverge at the 1e-1 scale).
    np.testing.assert_allclose(
        float(sharded), float(base), atol=3e-3, rtol=3e-3
    )


def test_mesh_without_expert_axis_is_fine():
    # cp_mesh doubles as the EP mesh; a CP/TP-only mesh (no "expert"
    # axis) must not crash — experts just stay replicated
    from torchft_tpu.parallel import make_mesh

    mesh = make_mesh({"data": 2, "model": 4})
    cfg = dataclasses.replace(tiny_moe_config(), cp_mesh=mesh)
    params = moe.init_params(cfg, jax.random.PRNGKey(0))
    tokens = _tokens(cfg)
    logits, _aux = moe.forward(cfg, params, tokens)
    assert np.isfinite(np.asarray(logits)).all()


def test_moe_trains_with_ft_stack():
    """One committed FT step on the MoE family: Manager + DummyCollectives
    + optax — the EP model plugs into the same transaction as the dense
    flagship."""
    from datetime import timedelta

    import optax

    from torchft_tpu import Lighthouse, Store
    from torchft_tpu.collectives import DummyCollectives
    from torchft_tpu.manager import Manager

    cfg = tiny_moe_config()
    params = moe.init_params(cfg, jax.random.PRNGKey(0))
    tx = optax.adamw(1e-3)
    opt_state = tx.init(params)
    tokens = _tokens(cfg)

    lighthouse = Lighthouse(
        bind="[::]:0", min_replicas=1, join_timeout_ms=200,
        quorum_tick_ms=50, heartbeat_timeout_ms=1000,
    )
    store = Store()
    manager = Manager(
        collectives=DummyCollectives(world_size=1),
        load_state_dict=lambda s: None,
        state_dict=lambda: {},
        min_replica_size=1,
        rank=0,
        world_size=1,
        use_async_quorum=False,
        timeout=timedelta(seconds=10),
        store_addr=store.address(),
        lighthouse_addr=lighthouse.address(),
        replica_id="moe_test",
    )
    try:
        manager.start_quorum()
        loss, grads = jax.value_and_grad(
            lambda p: moe.loss_fn(cfg, p, tokens)
        )(params)
        grads = manager.allreduce(grads).wait()
        assert manager.should_commit()
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        assert np.isfinite(float(loss))
    finally:
        manager.shutdown()
        store.shutdown()
        lighthouse.shutdown()
