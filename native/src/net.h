// TCP plumbing for the control plane: bind/listen, connect with exponential
// backoff + overall deadline (the role of reference src/net.rs + src/retry.rs),
// and blocking send/recv helpers with deadlines.
#pragma once

#include <atomic>
#include <cstdint>
#include <optional>
#include <stdexcept>
#include <string>
#include <vector>

namespace tft {

// Milliseconds since a fixed (steady) epoch; monotonic.
int64_t now_ms();
// Unix wall-clock milliseconds (for `Quorum.created_ms` and display).
int64_t unix_ms();
// "HH:MM:SS" (UTC) for dashboard/event-log display.
std::string format_unix_ms(int64_t ms);

std::string local_hostname();

struct Addr {
  std::string host;
  uint16_t port;
};

// Converts an absolute deadline into a poll() timeout in ms (-1 = none),
// throwing TimeoutError when the deadline has already passed.
int poll_timeout_or_throw(int64_t deadline_ms, const char* what);

// Accepts "host:port", "http://host:port", "tft://host:port", "[::]:port".
// Trailing path components ("host:port/prefix") are rejected; use
// split_store_addr for store addresses carrying a key prefix.
Addr parse_addr(const std::string& addr);

// Splits "host:port/some/prefix" into ("host:port", "some/prefix").
std::pair<std::string, std::string> split_store_addr(const std::string& addr);

class SocketError : public std::runtime_error {
 public:
  explicit SocketError(const std::string& msg) : std::runtime_error(msg) {}
};

class TimeoutError : public std::runtime_error {
 public:
  explicit TimeoutError(const std::string& msg) : std::runtime_error(msg) {}
};

// A payload integrity failure on a CRC-guarded wire frame (ring/stripe
// frames, heal stream ranges): the one failure class that must NEVER be
// folded into a generic socket error — a corrupted frame that commits is
// the exact silent-wrong-gradients scenario the commit vote cannot catch
// on its own. The "wire corruption:" message prefix is the cross-language
// contract: the ctypes bridge re-raises it as the typed Python
// ``WireCorruption`` so callers and the chaos harness can count
// detections.
class WireCorruptionError : public SocketError {
 public:
  explicit WireCorruptionError(const std::string& msg)
      : SocketError("wire corruption: " + msg) {}
};

// RAII fd wrapper. Movable, not copyable.
class Socket {
 public:
  Socket() = default;
  explicit Socket(int fd) : fd_(fd) {}
  Socket(Socket&& other) noexcept : fd_(other.fd_) { other.fd_ = -1; }
  Socket& operator=(Socket&& other) noexcept;
  ~Socket();

  int fd() const { return fd_; }
  bool valid() const { return fd_ >= 0; }
  void close();
  // Wakes any thread blocked in send/recv on this socket.
  void shutdown_rdwr();

  // Blocking IO with absolute deadline (now_ms()-based); deadline<0 = none.
  // Throws TimeoutError past the deadline, SocketError on EOF/reset.
  void send_all(const void* buf, size_t len, int64_t deadline_ms = -1);
  void recv_all(void* buf, size_t len, int64_t deadline_ms = -1);
  // Peek up to len bytes without consuming (for HTTP-vs-frame sniffing).
  size_t peek(void* buf, size_t len, int64_t deadline_ms = -1);

 private:
  void wait_ready(bool for_read, int64_t deadline_ms);
  int fd_ = -1;
};

class Listener {
 public:
  // Binds and listens; port 0 picks an ephemeral port.
  explicit Listener(const std::string& bind_addr);
  ~Listener();

  uint16_t port() const { return port_; }
  // Blocks until a connection arrives; returns invalid Socket after close().
  Socket accept();
  // As accept(), but throws TimeoutError past the deadline (deadline<0 = none).
  Socket accept(int64_t deadline_ms);
  void close();

 private:
  // Atomic: close() publishes -1 from one thread while accept() loads the
  // fd for its poll/accept calls from another (a plain int here is the
  // data race TSan flags first in this file). Loaded once per accept-loop
  // iteration so poll and ::accept see the same value.
  std::atomic<int> fd_{-1};
  uint16_t port_ = 0;
  // Self-pipe close() writes to so accept() always wakes: neither
  // shutdown() nor close() of a LISTENING fd interrupts a sibling thread
  // already blocked in poll() on it (POSIX leaves it undefined; Linux<4.5
  // and gVisor both leave the poller asleep forever) — the accept loop
  // polls the pipe's read end alongside the listen fd instead.
  int wake_rd_ = -1;
  int wake_wr_ = -1;
  std::atomic<bool> closed_{false};
};

// Single connect attempt with deadline (non-blocking connect + poll).
Socket connect_once(const Addr& addr, int64_t deadline_ms);

// Exponential backoff connect: 100ms initial, x1.5, max 10s, jittered,
// bounded by an overall timeout. Mirrors reference src/retry.rs:14-41.
Socket connect_with_retry(const std::string& addr, int64_t timeout_ms);

// Deterministic jittered exponential backoff schedule for retry loops (the
// manager's lease-renewal loop uses it so a dead lighthouse is not hammered
// at the fixed heartbeat interval by every group at once). failures <= 0
// yields 0; failure k waits base * 2^(k-1) capped at max_ms, scaled by a
// jitter factor in [0.5, 1.5) derived from splitmix64(seed ^ failures) —
// same (seed, failures) always yields the same delay, which is what makes
// the schedule unit-testable.
int64_t backoff_ms(int failures, int64_t base_ms, int64_t max_ms, uint64_t seed);

// Jittered interval for periodic work: interval scaled by [0.75, 1.25),
// deterministic in (seed, tick). Spreads renewal herds across groups.
int64_t jittered_interval_ms(int64_t interval_ms, uint64_t seed, uint64_t tick);

// Comma-separated endpoint list -> vector (whitespace stripped, empty
// entries dropped). THE parser for root failover sets
// (TORCHFT_LIGHTHOUSE_ROOT / TORCHFT_LH_PEERS): the manager, the region
// tier and the lighthouse must split the same wire format identically,
// so there is exactly one implementation.
std::vector<std::string> split_addr_list(const std::string& s);

} // namespace tft
