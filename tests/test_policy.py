"""Policy-engine tests: the cost model's crossovers, the decision rules
(sentinels, hysteresis, ties-to-current), and the voted transition's
split-brain-free guarantee across >= 2 real managers.
"""

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torchft_tpu import (
    FTTrainState,
    HostCollectives,
    Lighthouse,
    Manager,
    PolicyEngine,
    Store,
    StrategySpec,
)
from torchft_tpu.policy import (
    SENTINEL_COST_S,
    CostKnobs,
    default_candidates,
    strategy_cost,
)

logger = logging.getLogger(__name__)


def _grad_fn(params, x):
    def loss(p):
        return jnp.mean((x @ p["w"]) ** 2)

    value, grads = jax.value_and_grad(loss)(params)
    return value, grads


def _state():
    params = {"w": jnp.ones((8, 8), jnp.float32)}
    return FTTrainState(params, optax.sgd(0.1))


_BASE_SIG = dict(
    compute_s=0.01,
    wire_eff_MBps=4000.0,
    churn_per_min=0.0,
    ctrl_s=0.001,
    reconf_s=0.1,
    heal_s=3.0,
    world=2.0,
    model_bytes=4e6,
)


def _best(sig, knobs=None):
    knobs = knobs or CostKnobs()
    costs = {c.name: strategy_cost(c, sig, knobs) for c in default_candidates()}
    return min(costs, key=costs.get), costs


class TestStrategySpec:
    def test_validation(self):
        with pytest.raises(ValueError, match="kind"):
            StrategySpec("x", "warp")
        with pytest.raises(ValueError, match="per-step"):
            StrategySpec("x", "ddp", sync_every=4)
        with pytest.raises(ValueError, match="sync_every"):
            StrategySpec("x", "localsgd", sync_every=1)
        with pytest.raises(ValueError, match="wire"):
            StrategySpec("x", "diloco", sync_every=8, wire="fp4")
        with pytest.raises(ValueError, match="transport"):
            StrategySpec("x", "ddp", transport="warp")

    def test_wire_factor(self):
        assert StrategySpec("a", "ddp").wire_factor() == 1.0
        assert StrategySpec("b", "ddp", wire="bf16").wire_factor() == 0.5
        assert (
            StrategySpec("c", "diloco", sync_every=8, wire="q8").wire_factor()
            == 0.25
        )


class TestCostModel:
    """The crossovers the ISSUE names, pinned as orderings (not absolute
    numbers): per-step DDP on quiet fat links, DiLoCo(q8) when measured
    bandwidth drops below the computed crossover, longer outer windows as
    churn rises."""

    def test_quiet_fat_link_picks_per_step_ddp(self):
        # ddp_sharded's combined (q8 rs + bf16 ag) wire term undercuts
        # the f32 per-step candidates, so it may edge out plain ddp here
        # — either way a PER-STEP strategy wins the quiet fat link.
        best, costs = _best(dict(_BASE_SIG))
        assert best in ("ddp", "ddp_sharded"), costs

    def test_degraded_bandwidth_picks_diloco_q8(self):
        best, costs = _best(dict(_BASE_SIG, wire_eff_MBps=2.0))
        assert best.startswith("diloco_q8"), costs
        # and the q8 wire is doing real work: same strategy priced at the
        # f32 wire costs strictly more
        q8 = StrategySpec("q8", "diloco", sync_every=16, wire="q8")
        f32 = StrategySpec("f32", "diloco", sync_every=16)
        sig = dict(_BASE_SIG, wire_eff_MBps=2.0)
        assert strategy_cost(q8, sig, CostKnobs()) < strategy_cost(
            f32, sig, CostKnobs()
        )

    def test_rising_churn_prefers_longer_windows(self):
        # Among windowed candidates whose windows are LONG in wall time
        # (the production regime: seconds-scale steps, seconds-scale
        # heals), heavy churn tips the balance toward the LONGER window:
        # it hides more heal latency behind local compute and keeps most
        # faults outside the transaction+surfacing horizon, so fewer
        # windows discard (the Chameleon observation).
        h16 = StrategySpec("h16", "diloco", sync_every=16, wire="q8")
        h64 = StrategySpec("h64", "diloco", sync_every=64, wire="q8")
        sig_quiet = dict(
            _BASE_SIG, compute_s=0.05, heal_s=10.0, wire_eff_MBps=20.0
        )
        sig_churny = dict(sig_quiet, churn_per_min=2.0)
        k = CostKnobs()
        # the churn-induced relative penalty (cost under churn / cost
        # quiet) must be SMALLER for the longer window: it pays less per
        # fault, so rising churn shifts the balance toward it
        penalty16 = strategy_cost(h16, sig_churny, k) / strategy_cost(
            h16, sig_quiet, k
        )
        penalty64 = strategy_cost(h64, sig_churny, k) / strategy_cost(
            h64, sig_quiet, k
        )
        assert penalty64 < penalty16

    def test_fast_faults_prefer_tight_sync(self):
        # The flip side: when windows are SHORT in wall time (bench-scale
        # steps) every fault surfaces inside the next transaction and
        # discards the whole window — rapid faulting then favors the
        # per-step strategy, which only ever loses one step per fault.
        sig = dict(
            compute_s=0.03, wire_eff_MBps=500.0, churn_per_min=100.0,
            ctrl_s=0.003, reconf_s=0.05, heal_s=0.0, world=2.0,
            model_bytes=4 << 20,
        )
        k = CostKnobs(staleness_weight=0.0)
        ddp = StrategySpec("ddp", "ddp")
        h16 = StrategySpec("h16", "diloco", sync_every=16, wire="q8")
        assert strategy_cost(ddp, sig, k) < strategy_cost(h16, sig, k)
        # quiet, the same link orders the other way (amortized sync wins)
        assert strategy_cost(
            ddp, dict(sig, churn_per_min=0.0), k
        ) > strategy_cost(h16, dict(sig, churn_per_min=0.0), k)

    def test_unmeasured_bandwidth_does_not_price_the_wire(self):
        # Before the first sync there is no bandwidth sample: the model
        # must not invent one (it prices only fixed+control costs).
        sig = dict(_BASE_SIG, wire_eff_MBps=0.0)
        ddp = strategy_cost(StrategySpec("d", "ddp"), sig, CostKnobs())
        assert ddp < 0.1  # no 4 MB / 0 blowup

    def test_cost_is_deterministic(self):
        sig = dict(_BASE_SIG, churn_per_min=3.7, wire_eff_MBps=17.3)
        k = CostKnobs()
        spec = StrategySpec("h", "diloco", sync_every=16, wire="q8")
        assert strategy_cost(spec, sig, k) == strategy_cost(spec, sig, k)


class TestDecisionRules:
    def _engine(self, candidates=None, **kw):
        # Construction-only engine against a stub manager: the decision
        # rules are pure given costs.
        class _Stub:
            _use_async_quorum = False

            def has_iso_plane(self):
                return False

        eng = PolicyEngine.__new__(PolicyEngine)
        eng._manager = _Stub()
        eng._state = _state()
        eng._outer_tx = optax.sgd(0.7)
        eng._candidates = list(
            candidates
            or [
                StrategySpec("ddp", "ddp"),
                StrategySpec("diloco_q8_h16", "diloco", sync_every=16,
                             wire="q8"),
            ]
        )
        eng._avail = [True] * len(eng._candidates)
        eng._failed = [False] * len(eng._candidates)
        eng._current = 0
        eng._knobs = CostKnobs(**kw)
        eng._model_bytes = 4 << 20
        return eng

    def test_hysteresis_stands_still_on_near_ties(self):
        eng = self._engine(hysteresis=0.1)
        assert eng._choose([1.00, 0.95]) == 0  # within 10%: stay
        assert eng._choose([1.00, 0.85]) == 1  # clear win: move

    def test_exact_tie_falls_to_current(self):
        eng = self._engine(hysteresis=0.0)
        eng._current = 1
        assert eng._choose([1.0, 1.0]) == 1

    def test_sentineled_incumbent_must_move(self):
        eng = self._engine()
        assert eng._choose([SENTINEL_COST_S, 0.5]) == 1

    def test_all_sentineled_stands_still(self):
        eng = self._engine()
        assert eng._choose([SENTINEL_COST_S, SENTINEL_COST_S]) == 0

    def test_failed_candidate_carries_sentinel(self):
        eng = self._engine()
        eng._failed[1] = True
        agg = {
            **_BASE_SIG,
            "avail": np.ones(2),
            "failed": np.array([0.0, 1.0]),
        }
        costs = eng._costs(agg)
        assert costs[1] == SENTINEL_COST_S
        assert costs[0] < SENTINEL_COST_S

    def test_aggregate_excludes_zeroed_entries_and_takes_bottleneck(self):
        eng = self._engine()
        k = len(eng._candidates)

        def vec(ok, compute, bw, churn, intra=0.0, inter=0.0, opt_b=0.0):
            return np.asarray(
                [ok, compute, bw, churn, 0.001, 0.1, 0.0, intra, inter,
                 opt_b]
                + [1.0] * k + [0.0] * k,
                np.float64,
            )

        agg = eng._aggregate(
            [
                vec(1.0, 0.01, 100.0, 0.0, intra=800.0, inter=12.0,
                    opt_b=2048.0),
                vec(1.0, 0.02, 10.0, 2.0, intra=400.0),  # inter unmeasured
                vec(0.0, 0.0, 0.0, 0.0),  # healing/spare: zeroed, excluded
            ]
        )
        assert agg["compute_s"] == 0.02  # slowest paces the cohort
        assert agg["wire_eff_MBps"] == 10.0  # bottleneck link
        assert agg["churn_per_min"] == 2.0  # worst churn
        assert agg["world"] == 2.0
        # per-tier bottleneck: min over MEASURED (non-zero) entries only
        assert agg["tier_intra_MBps"] == 400.0
        assert agg["tier_inter_MBps"] == 12.0
        # worst resident optimizer state across live members
        assert agg["opt_state_bytes"] == 2048.0

    def test_backstop_sentinels_incumbent_and_falls_to_base(self):
        class _M:
            def incr(self, *a, **k):
                pass

        eng = self._engine()
        eng._manager.metrics = lambda: _M()
        eng._engines = {}
        eng._grad_fn = _grad_fn
        eng._consec_errors = 0
        eng._error_backstop = 8
        eng._current = 1  # the windowed candidate is the incumbent
        # 7 consecutive errored TRANSACTIONS: not yet (inner steps never
        # call _note_errored at all, so the run can only be broken by a
        # committed window in between)
        for _ in range(7):
            assert not eng._note_errored(True)
        # the 8th trips: incumbent sentineled, base adopted immediately
        assert eng._note_errored(True)
        assert eng._failed[1] is True
        assert eng._current == 0
        # a committed transaction resets the run
        eng._consec_errors = 5
        assert not eng._note_errored(False)
        assert eng._consec_errors == 0

    def test_aggregate_rejects_shape_mismatch(self):
        eng = self._engine()
        with pytest.raises(RuntimeError, match="no live"):
            eng._aggregate([np.asarray([1.0, 2.0])])

    def test_construction_gates_diloco_without_outer_tx(self):
        lighthouse = Lighthouse(
            bind="[::]:0", min_replicas=1, join_timeout_ms=200,
            quorum_tick_ms=50, heartbeat_timeout_ms=2000,
        )
        store = Store()
        manager = Manager(
            collectives=HostCollectives(timeout=timedelta(seconds=10)),
            load_state_dict=lambda s: None,
            state_dict=lambda: {},
            min_replica_size=1,
            rank=0,
            world_size=1,
            use_async_quorum=False,
            store_addr=store.address(),
            lighthouse_addr=lighthouse.address(),
            replica_id="gate_test",
        )
        try:
            eng = PolicyEngine(manager, _state(), _grad_fn, outer_tx=None)
            names = [c.name for c in eng._candidates]
            for i, name in enumerate(names):
                if name.startswith("diloco"):
                    assert not eng._avail[i]
                if name.startswith("ddp") or name.startswith("localsgd"):
                    assert eng._avail[i]
        finally:
            manager.shutdown()
            store.shutdown()
            lighthouse.shutdown()


class TestSoloEndToEnd:
    def test_trains_and_decides_on_cadence(self):
        lighthouse = Lighthouse(
            bind="[::]:0", min_replicas=1, join_timeout_ms=200,
            quorum_tick_ms=50, heartbeat_timeout_ms=2000,
        )
        store = Store()
        state = _state()
        policy = None
        manager = Manager(
            collectives=HostCollectives(timeout=timedelta(seconds=10)),
            load_state_dict=lambda s: policy.load_state_dict(s),
            state_dict=lambda: policy.state_dict(),
            min_replica_size=1,
            rank=0,
            world_size=1,
            use_async_quorum=False,
            store_addr=store.address(),
            lighthouse_addr=lighthouse.address(),
            replica_id="policy_solo",
        )
        try:
            policy = PolicyEngine(
                manager, state, _grad_fn, outer_tx=optax.sgd(0.7),
                decide_every=8,
            )
            x = jnp.ones((4, 8), jnp.float32)
            start = policy.strategy.name
            for _ in range(20):
                loss = policy.step(x)
            policy.flush()
            assert np.isfinite(float(loss))
            assert len(policy.decisions) >= 2
            for d in policy.decisions:
                assert d["committed"] is True
                assert set(d["costs"]) == {
                    c.name for c in policy._candidates
                }
            assert manager.metrics().snapshot()["counters"][
                "policy_decisions"
            ] == len(policy.decisions)
            # solo on an unmeasured loopback: no reason to leave the
            # starting strategy unless a decision said so — and every
            # decision must be internally consistent
            for d in policy.decisions:
                if d["switched"]:
                    assert d["to"] != d["from"]
            assert policy.strategy.name in {start} | {
                d["to"] for d in policy.decisions if d["switched"]
            }
        finally:
            manager.shutdown()
            store.shutdown()
            lighthouse.shutdown()

    def test_state_dict_roundtrip_carries_strategy_and_clocks(self):
        lighthouse = Lighthouse(
            bind="[::]:0", min_replicas=1, join_timeout_ms=200,
            quorum_tick_ms=50, heartbeat_timeout_ms=2000,
        )
        store = Store()
        state = _state()
        manager = Manager(
            collectives=HostCollectives(timeout=timedelta(seconds=10)),
            load_state_dict=lambda s: None,
            state_dict=lambda: {},
            min_replica_size=1,
            rank=0,
            world_size=1,
            use_async_quorum=False,
            store_addr=store.address(),
            lighthouse_addr=lighthouse.address(),
            replica_id="policy_sd",
        )
        try:
            cands = [
                StrategySpec("ddp", "ddp"),
                StrategySpec("localsgd_h4", "localsgd", sync_every=4),
            ]
            policy = PolicyEngine(
                manager, state, _grad_fn, candidates=cands, decide_every=64
            )
            x = jnp.ones((4, 8), jnp.float32)
            for _ in range(3):
                policy.step(x)
            sd = policy.state_dict()

            state2 = _state()
            policy2 = PolicyEngine(
                manager, state2, _grad_fn, candidates=cands, decide_every=64
            )
            policy2.load_state_dict(sd)
            assert policy2._ticks == policy._ticks
            assert policy2._current == policy._current
            np.testing.assert_array_equal(
                np.asarray(state2.params["w"]), np.asarray(state.params["w"])
            )
        finally:
            manager.shutdown()
            store.shutdown()
            lighthouse.shutdown()


class _PolicyRunner:
    """Two replica groups as threads against one lighthouse: the e2e
    harness for voted transitions (the test_manager_integ pattern, with a
    PolicyEngine loop instead of OptimizerWrapper)."""

    def __init__(self, num_groups=2, decide_every=4, steps=14,
                 fail_decide_epoch=None, candidates=None, big_model=True):
        self.num_groups = num_groups
        self.decide_every = decide_every
        self.steps = steps
        self.fail_decide_epoch = fail_decide_epoch
        self.candidates = candidates or [
            StrategySpec("ddp", "ddp"),
            StrategySpec("diloco_q8_h4", "diloco", sync_every=4, wire="q8"),
        ]
        self.big_model = big_model
        self.barrier = threading.Barrier(num_groups)

    def _worker(self, gid, lighthouse_addr):
        store = Store()
        state = _state()
        policy = None
        manager = Manager(
            collectives=HostCollectives(timeout=timedelta(seconds=30)),
            load_state_dict=lambda s: policy.load_state_dict(s),
            state_dict=lambda: policy.state_dict(),
            min_replica_size=self.num_groups,
            rank=0,
            world_size=1,
            use_async_quorum=False,
            timeout=timedelta(seconds=30),
            quorum_timeout=timedelta(seconds=30),
            store_addr=store.address(),
            lighthouse_addr=lighthouse_addr,
            replica_id=f"pol_{gid}",
        )
        try:
            policy = PolicyEngine(
                manager, state, _grad_fn, outer_tx=optax.sgd(0.7),
                candidates=self.candidates,
                decide_every=self.decide_every,
            )
            # Scripted conditions, identical on every member: a degraded
            # measured link and a model large enough that the windowed-q8
            # candidate must win the cost model decisively.
            if self.big_model:
                policy._model_bytes = 64 << 20
                manager.signals = lambda w=600.0: {
                    "churn_per_min": 0.0,
                    "wire_eff_MBps": 2.0,
                    "heal": None,
                }
            if self.fail_decide_epoch is not None:
                orig_allgather = manager.allgather
                runner = self

                def failing_allgather(tree):
                    if (
                        isinstance(tree, dict)
                        and "policy_sig" in tree
                        and gid == 1
                        and policy._decide_epoch == runner.fail_decide_epoch
                    ):
                        # A member failure DURING the transition, of the
                        # ring-visible class (a dying/desynced member
                        # ships a garbage frame): the native op-mismatch
                        # fail-fast propagates to EVERY member, everyone's
                        # error latches, and the whole cohort must abort
                        # the switch together.
                        tree = {
                            "policy_sig": np.zeros(3, np.float64)
                        }
                    return orig_allgather(tree)

                manager.allgather = failing_allgather

            x = jnp.ones((4, 8), jnp.float32)
            self.barrier.wait(timeout=60)
            for _ in range(self.steps):
                policy.step(x)
            policy.flush()
            return {
                "gid": gid,
                "strategy": policy.strategy.name,
                "decisions": policy.decisions,
                "params": np.asarray(state.params["w"]),
                "steps": manager.current_step(),
            }
        finally:
            manager.shutdown()
            store.shutdown()

    def run(self):
        lighthouse = Lighthouse(
            bind="[::]:0", min_replicas=self.num_groups, join_timeout_ms=500,
            quorum_tick_ms=50, heartbeat_timeout_ms=5000,
        )
        try:
            with ThreadPoolExecutor(max_workers=self.num_groups) as ex:
                futs = [
                    ex.submit(self._worker, gid, lighthouse.address())
                    for gid in range(self.num_groups)
                ]
                return sorted(
                    (f.result(timeout=180) for f in futs),
                    key=lambda r: r["gid"],
                )
        finally:
            lighthouse.shutdown()


class TestVotedTransition:
    """Acceptance: a strategy switch across >= 2 managers is all-or-nothing
    — committed everywhere, or aborted everywhere by any member's failure."""

    def test_cohort_switches_together(self):
        results = _PolicyRunner(steps=14, decide_every=4).run()
        a, b = results
        # Both members made the same decisions in the same order...
        assert len(a["decisions"]) >= 1
        assert [
            (d["from"], d["to"], d["committed"]) for d in a["decisions"]
        ] == [(d["from"], d["to"], d["committed"]) for d in b["decisions"]]
        # ...the scripted degraded link forced the q8 window strategy...
        assert a["strategy"] == b["strategy"] == "diloco_q8_h4"
        assert any(d["switched"] for d in a["decisions"])
        switch = next(d for d in a["decisions"] if d["switched"])
        assert switch["signals"]["wire_eff_MBps"] == 2.0  # the trigger
        # ...and training stayed bit-identical across the cohort.
        np.testing.assert_array_equal(a["params"], b["params"])

    def test_member_failure_aborts_transition_for_all(self):
        # Member 1 fails during decision epoch 0 (the first attempted
        # switch). The AND-vote must abort the transition on BOTH members
        # — no state where one switched and one didn't — and the NEXT
        # clean decision completes the switch on both.
        results = _PolicyRunner(
            steps=18, decide_every=4, fail_decide_epoch=0
        ).run()
        a, b = results
        assert [
            (d["from"], d["to"], d["committed"], d["switched"])
            for d in a["decisions"]
        ] == [
            (d["from"], d["to"], d["committed"], d["switched"])
            for d in b["decisions"]
        ]
        first_a, first_b = a["decisions"][0], b["decisions"][0]
        # the injected failure aborted epoch 0 everywhere
        assert first_a["committed"] is False and first_a["switched"] is False
        assert first_b["committed"] is False and first_b["switched"] is False
        # at no point did exactly one member hold the new strategy: the
        # per-epoch (from, to, switched) tuples are identical, so the
        # strategy history is identical — and the run converged to the
        # same final strategy with bit-identical params.
        assert a["strategy"] == b["strategy"]
        later = [d for d in a["decisions"][1:] if d["switched"]]
        assert later, "a later clean decision should complete the switch"
        np.testing.assert_array_equal(a["params"], b["params"])


class TestPerTierPricing:
    """Satellite of the shm-tier PR: hier candidates are priced on the
    BOTTLENECK tier's measured bandwidth, not the folded flat average."""

    def test_hier_spec_validation(self):
        with pytest.raises(ValueError, match="plan transport"):
            StrategySpec("x", "ddp", hier=True)
        with pytest.raises(ValueError, match="localsgd"):
            StrategySpec("x", "localsgd", sync_every=8, hier=True)
        spec = StrategySpec("x", "ddp", transport="plan", hier=True)
        assert spec.hier

    def test_topology_labeled_ladder_gains_hier_candidate(self):
        names = [c.name for c in default_candidates()]
        assert "ddp_plan_hier" not in names  # unlabeled: exact old ladder
        names = [c.name for c in default_candidates(topology_labeled=True)]
        assert "ddp_plan_hier" in names
        assert names.index("ddp_plan_hier") == names.index("ddp_plan") + 1

    def test_hier_cost_prices_bottleneck_tier_not_folded_average(self):
        knobs = CostKnobs(staleness_weight=0.0, sync_fixed_s=0.0)
        model = 8 * (1 << 20)
        sig = dict(
            compute_s=0.01, churn_per_min=0.0, ctrl_s=0.0, reconf_s=0.0,
            heal_s=0.0, world=4.0, model_bytes=float(model),
            # Folded flat average is FAST (the shm tier inflates it)...
            wire_eff_MBps=500.0,
            # ...but the inter tier is the real bottleneck.
            tier_intra_MBps=400.0,
            tier_inter_MBps=10.0,
        )
        flat = StrategySpec("ddp_plan", "ddp", transport="plan")
        hier = StrategySpec("h", "ddp", transport="plan", hier=True)
        c_flat = strategy_cost(flat, sig, knobs)
        c_hier = strategy_cost(hier, sig, knobs)
        # flat priced on the folded average: 8 MB / 500 MBps = 16 ms
        assert c_flat == pytest.approx(0.01 + 8 / 500.0, rel=1e-6)
        # hier priced on max(inter leg, intra leg):
        #   inter: 8 MB / 10 MBps = 0.8 s; intra: 16 MB / 400 = 40 ms
        assert c_hier == pytest.approx(0.01 + 8 / 10.0, rel=1e-6)
        # a q8 hier wire compresses the bottleneck leg 4x; the intra leg
        # (full width) now competes but inter still bounds
        hier_q8 = StrategySpec(
            "hq", "ddp", transport="plan", hier=True, wire="q8",
        )
        c_q8 = strategy_cost(hier_q8, sig, knobs)
        assert c_q8 == pytest.approx(0.01 + 2 / 10.0, rel=1e-6)
        # unmeasured tiers: hier falls back to the flat pricing
        sig2 = dict(sig, tier_intra_MBps=0.0, tier_inter_MBps=0.0)
        assert strategy_cost(hier, sig2, knobs) == pytest.approx(
            c_flat, rel=1e-6
        )

    def test_manager_folds_tier_stats_into_signals(self):
        from torchft_tpu.manager import Manager

        class _FakeTierCollectives:
            def __init__(self):
                self._stats = [{
                    "op": "allreduce_hier",
                    "ring": 0.5,
                    "wire_bytes": 4 << 20,
                    "tiers": {
                        "host": {"tx_bytes": 0, "shm_bytes": 32 << 20,
                                 "rs_s": 0.004, "ag_s": 0.004,
                                 "bcast_s": 0.008, "world": 4, "eff": 1,
                                 "leader": True, "transport": "shm"},
                        "intra": {"tx_bytes": 8 << 20, "rs_s": 0.05,
                                  "ag_s": 0.05, "bcast_s": 0.06,
                                  "world": 2, "eff": 1},
                        "inter": {"tx_bytes": 4 << 20, "ring_s": 0.4,
                                  "world": 2, "eff": 1, "leader": True},
                    },
                }]

            def pop_op_stats(self):
                out, self._stats = self._stats, []
                return out

        mgr = Manager.__new__(Manager)  # signals-path state only
        from torchft_tpu.metrics import Metrics

        mgr._collectives = _FakeTierCollectives()
        mgr._metrics = Metrics()
        mgr._last_wire_eff_mbps = None
        mgr._last_tier_mbps = {}
        mgr._checkpoint_transport = object()
        entries = mgr.observe_op_stats()
        assert len(entries) == 1
        sig = mgr.signals()
        tiers = sig["tier_eff_MBps"]
        # host: 32 MiB over 16 ms = 2000 MB/s; intra: 8 MiB / 0.16 s =
        # 50 MB/s; inter: 4 MiB / 0.4 s = 10 MB/s
        assert tiers["host"] == pytest.approx(2000.0, rel=0.01)
        assert tiers["intra"] == pytest.approx(50.0, rel=0.01)
        assert tiers["inter"] == pytest.approx(10.0, rel=0.01)
