// Per-connection thread bookkeeping shared by the three servers. Handler
// threads are detached and self-reap (remove their fd and wake shutdown), so
// long-lived servers don't accumulate zombie threads or stale fd numbers.
#pragma once

#include <condition_variable>
#include <cstdint>
#include <map>
#include <mutex>
#include <sys/socket.h>
#include <thread>

#include "net.h"

namespace tft {

class ConnTracker {
 public:
  // Spawns a detached handler thread for sock. Returns false (dropping the
  // connection) if shutdown already started.
  template <typename Fn>
  bool spawn(Socket sock, Fn fn) {
    uint64_t id;
    {
      std::lock_guard<std::mutex> lock(mu_);
      if (shutting_down_) return false;
      id = next_id_++;
      fds_[id] = sock.fd();
      active_++;
    }
    std::thread([this, id, s = std::move(sock), fn = std::move(fn)]() mutable {
      fn(s);
      std::lock_guard<std::mutex> lock(mu_);
      fds_.erase(id);
      active_--;
      cv_.notify_all();
    }).detach();
    return true;
  }

  // Wakes all handlers blocked in socket IO and waits until every handler
  // thread has finished. Callers must first unblock handlers waiting on
  // their own condition variables.
  void shutdown_all() {
    std::unique_lock<std::mutex> lock(mu_);
    shutting_down_ = true;
    for (const auto& [id, fd] : fds_) ::shutdown(fd, SHUT_RDWR);
    cv_.wait(lock, [&] { return active_ == 0; });
  }

 private:
  std::mutex mu_;
  std::condition_variable cv_;
  std::map<uint64_t, int> fds_;
  uint64_t next_id_ = 0;
  size_t active_ = 0;
  bool shutting_down_ = false;
};

} // namespace tft
