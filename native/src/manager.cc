#include "manager.h"

#include <sys/socket.h>

#include <unistd.h>

#include <cctype>
#include <chrono>
#include <functional>

#include "log.h"
#include "wire.h"

namespace tft {

using torchft_tpu::ErrorResponse;
using torchft_tpu::Quorum;
using torchft_tpu::QuorumMember;

// ---- LighthouseClient ----

LighthouseClient::LighthouseClient(const std::string& addr,
                                   int64_t connect_timeout_ms)
    : addr_(addr), connect_timeout_ms_(connect_timeout_ms) {}

Quorum LighthouseClient::quorum(const QuorumMember& requester, int64_t timeout_ms,
                                int64_t connect_timeout_ms) {
  torchft_tpu::LighthouseQuorumRequest req;
  *req.mutable_requester() = requester;
  req.set_timeout_ms(timeout_ms);
  auto resp = call<torchft_tpu::LighthouseQuorumRequest,
                   torchft_tpu::LighthouseQuorumResponse>(
      addr_, MsgType::kLighthouseQuorumReq, req, MsgType::kLighthouseQuorumResp,
      connect_timeout_ms > 0 ? connect_timeout_ms : connect_timeout_ms_,
      timeout_ms);
  return resp.quorum();
}

template <typename Req, typename Resp>
Resp LighthouseClient::roundtrip(uint8_t req_type, const Req& req,
                                 uint8_t resp_type, int64_t timeout_ms) {
  MutexLock lock(hb_mu_);
  int64_t deadline = now_ms() + timeout_ms;
  if (!hb_sock_.valid()) hb_sock_ = connect_with_retry(addr_, timeout_ms);
  try {
    send_msg(hb_sock_, static_cast<MsgType>(req_type), req, deadline);
    return recv_expect<Resp>(hb_sock_, static_cast<MsgType>(resp_type), deadline);
  } catch (...) {
    hb_sock_.close(); // reconnect on next call
    throw;
  }
}

void LighthouseClient::heartbeat(const std::string& replica_id, int64_t timeout_ms) {
  torchft_tpu::LighthouseHeartbeatRequest req;
  req.set_replica_id(replica_id);
  roundtrip<torchft_tpu::LighthouseHeartbeatRequest,
            torchft_tpu::LighthouseHeartbeatResponse>(
      static_cast<uint8_t>(MsgType::kLighthouseHeartbeatReq), req,
      static_cast<uint8_t>(MsgType::kLighthouseHeartbeatResp), timeout_ms);
}

int64_t LighthouseClient::lease_renew(const std::vector<LeaseEntry>& entries,
                                      int64_t timeout_ms) {
  torchft_tpu::LeaseRenewRequest req;
  lease_entries_to_pb(entries, &req);
  auto resp = roundtrip<torchft_tpu::LeaseRenewRequest,
                        torchft_tpu::LeaseRenewResponse>(
      static_cast<uint8_t>(MsgType::kLeaseRenewReq), req,
      static_cast<uint8_t>(MsgType::kLeaseRenewResp), timeout_ms);
  return resp.quorum_id();
}

void LighthouseClient::depart(const std::string& replica_id, int64_t timeout_ms) {
  torchft_tpu::DepartRequest req;
  req.set_replica_id(replica_id);
  roundtrip<torchft_tpu::DepartRequest, torchft_tpu::DepartResponse>(
      static_cast<uint8_t>(MsgType::kDepartReq), req,
      static_cast<uint8_t>(MsgType::kDepartResp), timeout_ms);
}

// ---- ManagerServer ----

ManagerServer::ManagerServer(const std::string& replica_id,
                             const std::string& lighthouse_addr,
                             const std::string& hostname, const std::string& bind,
                             const std::string& store_addr, uint64_t world_size,
                             int64_t heartbeat_interval_ms,
                             int64_t connect_timeout_ms,
                             const std::string& root_addr, int64_t lease_ttl_ms,
                             const std::string& region,
                             const std::string& host,
                             int64_t region_probe_max)
    : replica_id_(replica_id),
      lighthouse_addr_(lighthouse_addr),
      root_addr_(root_addr == lighthouse_addr ? "" : root_addr),
      hostname_(hostname.empty() ? local_hostname() : hostname),
      store_addr_(store_addr),
      region_(region),
      host_label_(host),
      world_size_(world_size),
      heartbeat_interval_ms_(heartbeat_interval_ms),
      connect_timeout_ms_(connect_timeout_ms),
      lease_ttl_ms_(lease_ttl_ms),
      region_probe_max_(region_probe_max),
      listener_(std::make_unique<Listener>(bind)) {
  for (const auto& addr : split_addr_list(lighthouse_addr_)) {
    lighthouse_clients_.push_back(
        std::make_unique<LighthouseClient>(addr, connect_timeout_ms));
  }
  if (lighthouse_clients_.empty()) {
    throw std::runtime_error("manager: empty lighthouse address");
  }
  for (const auto& addr : split_addr_list(root_addr_)) {
    root_clients_.push_back(
        std::make_unique<LighthouseClient>(addr, connect_timeout_ms));
  }
  // Fail fast if the lighthouse is unreachable, mirroring the reference's
  // connect-at-construction (src/manager.rs:97). Endpoint lists are tried
  // in order (a standby root rejects with UNAVAILABLE and we move on);
  // with a root fallback configured, a dead region demotes us at
  // construction instead of failing.
  std::string last_err;
  bool connected = false;
  size_t start_idx = 0;
  for (size_t i = 0; i < lighthouse_clients_.size() && !connected; i++) {
    try {
      lighthouse_clients_[i]->heartbeat(replica_id_, connect_timeout_ms);
      connected = true;
      start_idx = i;
    } catch (const std::exception& e) {
      last_err = e.what();
    }
  }
  if (connected) {
    MutexLock lock(lh_mu_);
    lh_idx_ = start_idx;
  } else {
    if (root_clients_.empty()) {
      throw std::runtime_error("lighthouse unreachable at startup: " +
                               last_err);
    }
    LOG_WARN("region lighthouse " << lighthouse_addr_ << " unreachable at "
                                  << "startup (" << last_err
                                  << "); registering directly at root");
    bool root_ok = false;
    for (size_t i = 0; i < root_clients_.size() && !root_ok; i++) {
      try {
        root_clients_[i]->heartbeat(replica_id_, connect_timeout_ms);
        root_ok = true;
        start_idx = i;
      } catch (const std::exception& e) {
        last_err = e.what();
      }
    }
    if (!root_ok) {
      throw std::runtime_error("no lighthouse or root endpoint reachable: " +
                               last_err);
    }
    MutexLock lock(lh_mu_);
    using_root_ = true;
    root_idx_ = start_idx;
  }
  accept_thread_ = std::thread([this] { accept_loop(); });
  heartbeat_thread_ = std::thread([this] { heartbeat_loop(); });
  LOG_INFO("Manager " << replica_id_ << " listening on " << address());
}

ManagerServer::~ManagerServer() { shutdown(); }

std::string ManagerServer::address() const {
  return "http://" + hostname_ + ":" + std::to_string(listener_->port());
}

void ManagerServer::shutdown() {
  {
    // Flag + notify under the cv's mutex so waiters can't miss the wakeup.
    MutexLock lock(mu_);
    if (shutting_down_.exchange(true)) return;
    quorum_cv_.notify_all();
    commit_cv_.notify_all();
    hb_cv_.notify_all();
  }
  listener_->close();
  if (accept_thread_.joinable()) accept_thread_.join();
  if (heartbeat_thread_.joinable()) heartbeat_thread_.join();
  conns_.shutdown_all();
}

bool ManagerServer::using_root_fallback() {
  MutexLock lock(lh_mu_);
  return using_root_;
}

bool ManagerServer::region_probe_given_up() {
  MutexLock lock(lh_mu_);
  return probe_given_up_;
}

void ManagerServer::set_status_json(const std::string& status_json) {
  MutexLock lock(mu_);
  status_json_ = status_json;
}

ManagerServer::EndpointPick ManagerServer::pick_endpoint() {
  MutexLock lock(lh_mu_);
  EndpointPick pick;
  pick.on_root = using_root_ && !root_clients_.empty();
  if (pick.on_root) {
    pick.idx = root_idx_ % root_clients_.size();
    pick.client = root_clients_[pick.idx].get();
  } else {
    pick.idx = lh_idx_ % lighthouse_clients_.size();
    pick.client = lighthouse_clients_[pick.idx].get();
  }
  return pick;
}

void ManagerServer::rotate_if_current(const EndpointPick& pick) {
  MutexLock lock(lh_mu_);
  bool on_root = using_root_ && !root_clients_.empty();
  if (on_root != pick.on_root) return;  // the list itself changed
  if (on_root) {
    if (root_clients_.size() > 1 && root_idx_ % root_clients_.size() == pick.idx)
      root_idx_ = (pick.idx + 1) % root_clients_.size();
  } else if (lighthouse_clients_.size() > 1 &&
             lh_idx_ % lighthouse_clients_.size() == pick.idx) {
    lh_idx_ = (pick.idx + 1) % lighthouse_clients_.size();
  }
}

void ManagerServer::accept_loop() {
  while (!shutting_down_) {
    Socket sock = listener_->accept();
    if (!sock.valid()) return;
    conns_.spawn(std::move(sock), [this](Socket& s) { handle_conn(s); });
  }
}

// Lease-renewal loop (the old heartbeat loop, upgraded three ways): the
// renewal carries the manager's lease TTL, the healthy-path interval is
// jittered so thousands of groups don't renew in lockstep, and a failing
// lighthouse gets jittered EXPONENTIAL backoff instead of being hammered at
// the fixed interval by every group simultaneously. With a root fallback
// configured, two consecutive failures demote the group to direct-root
// registration; the dead region is re-probed once per lease TTL and wins
// the group back when it answers.
void ManagerServer::heartbeat_loop() {
  const uint64_t seed = std::hash<std::string>{}(replica_id_);
  uint64_t tick = 0;
  int failures = 0;
  int probe_failures = 0;
  int64_t next_region_probe_ms = 0;
  const int64_t probe_interval_ms =
      lease_ttl_ms_ > 0 ? lease_ttl_ms_ : heartbeat_interval_ms_ * 10;
  while (!shutting_down_) {
    bool probing_enabled;
    {
      MutexLock lock(lh_mu_);
      probing_enabled = !probe_given_up_;
    }
    EndpointPick pick = pick_endpoint();
    bool on_root = pick.on_root;
    try {
      std::vector<LeaseEntry> entries(1);
      entries[0].replica_id = replica_id_;
      entries[0].ttl_ms = lease_ttl_ms_;
      {
        MutexLock lock(mu_);
        entries[0].status_json = status_json_;
      }
      pick.client->lease_renew(entries, heartbeat_interval_ms_ * 10);
      failures = 0;
    } catch (const std::exception& e) {
      failures += 1;
      LOG_WARN("lease renewal to " << (on_root ? "root" : "lighthouse")
                                   << " failed (x" << failures
                                   << "): " << e.what());
      // Rotate to the next endpoint of the active list: a killed or
      // deposed root (a standby answers UNAVAILABLE) hands the group to
      // the next member of the failover set on the very next renewal
      // instead of camping on a dead address. Compare-and-rotate so a
      // concurrent quorum-forward failure can't double-rotate us past
      // the live endpoint.
      rotate_if_current(pick);
      if (!on_root && failures >= 2 * static_cast<int>(lighthouse_clients_.size())
          && !root_clients_.empty()) {
        LOG_WARN("region lighthouse " << lighthouse_addr_
                                      << " unresponsive; demoting "
                                      << replica_id_
                                      << " to direct root registration");
        MutexLock lock(lh_mu_);
        using_root_ = true;
        failures = 0;
      }
    }
    if (on_root && probing_enabled && now_ms() >= next_region_probe_ms) {
      next_region_probe_ms = now_ms() + probe_interval_ms;
      try {
        lighthouse_clients_[0]->heartbeat(replica_id_,
                                          heartbeat_interval_ms_ * 5);
        LOG_INFO("region lighthouse " << lighthouse_addr_
                                      << " is back; leaving root fallback");
        MutexLock lock(lh_mu_);
        using_root_ = false;
        probe_failures = 0;
      } catch (const std::exception&) {
        // still down; stay on the root
        probe_failures += 1;
        if (region_probe_max_ > 0 && probe_failures >= region_probe_max_) {
          // Bounded give-up: a region that is GONE from the topology
          // (not merely restarting) would otherwise eat one doomed
          // connect attempt per TTL for the rest of this tenure.
          LOG_WARN("region lighthouse "
                   << lighthouse_addr_ << " still unreachable after "
                   << probe_failures
                   << " re-probes; giving up — staying on the root");
          MutexLock lock(lh_mu_);
          probe_given_up_ = true;
        }
      }
    }
    int64_t sleep_ms =
        failures == 0
            ? jittered_interval_ms(heartbeat_interval_ms_, seed, tick++)
            : backoff_ms(failures, heartbeat_interval_ms_, 10000, seed);
    UniqueMutexLock lock(mu_);
    if (!shutting_down_)
      hb_cv_.wait_for(lock, std::chrono::milliseconds(sleep_ms));
  }
}

void ManagerServer::handle_conn(Socket& sock) {
  try {
    while (true) {
      auto [type, payload] = recv_frame(sock);
      switch (type) {
        case MsgType::kManagerQuorumReq:
          handle_quorum(sock, payload);
          break;
        case MsgType::kCheckpointMetadataReq: {
          torchft_tpu::CheckpointMetadataRequest req;
          req.ParseFromString(payload);
          std::optional<std::string> metadata;
          {
            MutexLock lock(mu_);
            auto it = checkpoint_metadata_.find(req.rank());
            if (it != checkpoint_metadata_.end()) metadata = it->second;
          }
          if (!metadata.has_value()) {
            send_error(sock, ErrorResponse::INVALID_ARGUMENT, "rank not found");
          } else {
            torchft_tpu::CheckpointMetadataResponse resp;
            resp.set_checkpoint_metadata(*metadata);
            send_msg(sock, MsgType::kCheckpointMetadataResp, resp);
          }
          break;
        }
        case MsgType::kShouldCommitReq:
          handle_should_commit(sock, payload);
          break;
        case MsgType::kKillReq: {
          torchft_tpu::KillRequest req;
          req.ParseFromString(payload);
          LOG_WARN("got kill request: " << req.msg());
          // Reference src/manager.rs:349-354: hard exit, torchelastic-style
          // supervision is responsible for restarting the trainer.
          _exit(1);
        }
        default:
          send_error(sock, ErrorResponse::INVALID_ARGUMENT,
                     "unexpected message type");
          return;
      }
    }
  } catch (const std::exception&) {
    // peer went away
  }
}

void ManagerServer::handle_quorum(Socket& sock, const std::string& payload) {
  torchft_tpu::ManagerQuorumRequest req;
  if (!req.ParseFromString(payload)) {
    send_error(sock, ErrorResponse::INVALID_ARGUMENT, "bad quorum request");
    return;
  }
  LOG_INFO("got quorum request for rank " << req.rank());
  int64_t deadline = req.timeout_ms() <= 0 ? -1 : now_ms() + req.timeout_ms();

  UniqueMutexLock lock(mu_);
  // Stash checkpoint server info for the healing flow.
  checkpoint_metadata_[req.rank()] = req.checkpoint_metadata();
  participants_.insert(req.rank());
  if (req.force_reconfigure()) force_reconfigure_pending_ = true;
  int64_t gen = quorum_gen_;

  if (participants_.size() >= world_size_) {
    // Last local rank arrived: forward one request to the lighthouse on
    // behalf of the whole replica group.
    participants_.clear();
    LOG_INFO("all workers joined -- starting quorum");
    QuorumMember requester;
    requester.set_replica_id(replica_id_);
    requester.set_address(address());
    requester.set_store_address(store_addr_);
    requester.set_step(req.step());
    requester.set_world_size(world_size_);
    requester.set_shrink_only(req.shrink_only());
    requester.set_region(region_);
    requester.set_host(host_label_);
    requester.set_force_reconfigure(force_reconfigure_pending_);
    force_reconfigure_pending_ = false;
    // The state lock is NOT held across the lighthouse round trip (the
    // reference's src/manager.rs:181 TODO, carried here until this fix):
    // the quorum RPC long-polls the join window — seconds against a slow
    // or stalled root — and with mu_ held, every lease renewal's status
    // snapshot, checkpoint-metadata lookup and should_commit barrier on
    // other connections serialized behind it. Release, call, re-acquire,
    // and REVALIDATE via the quorum generation: everything this block
    // needed from the state was copied into `requester` above, and the
    // generation tells us whether a sibling forward published a NEWER
    // result while the lock was free (possible when client timeouts
    // re-register the ranks and another thread sees the set full) — an
    // older result or error must then be dropped, not installed over it.
    lock.unlock();
    std::optional<Quorum> got;
    std::string err;
    ErrorResponse::Code err_code = ErrorResponse::UNAVAILABLE;
    // Forward with bounded endpoint-walk retries INSIDE the client's own
    // deadline: a root killed mid-poll (connection reset) or a standby's
    // UNAVAILABLE rejection rotates and retries the next endpoint of the
    // failover set, so a root failover is transparent at the manager
    // boundary — callers see at worst added latency, not an error, and
    // quorums re-form without any trainer-process restart. Deadline
    // exhaustion still surfaces as the reference's TimeoutError mapping.
    int64_t fw_deadline = req.timeout_ms() <= 0 ? -1 : now_ms() + req.timeout_ms();
    // With a failover set, one dead endpoint must not spend the whole
    // quorum deadline in connect retries: bound per-attempt connects and
    // walk on. A single-endpoint manager keeps the classic full-window
    // connect (pre-failover semantics).
    bool multi = lighthouse_clients_.size() + root_clients_.size() > 1;
    int64_t attempt_connect_ms =
        multi ? std::min<int64_t>(connect_timeout_ms_, 3000) : -1;
    while (true) {
      EndpointPick pick = pick_endpoint();
      int64_t remain =
          fw_deadline < 0 ? req.timeout_ms() : fw_deadline - now_ms();
      if (fw_deadline >= 0 && remain <= 0) {
        err = "lighthouse quorum timed out across root endpoints";
        err_code = ErrorResponse::DEADLINE_EXCEEDED;
        break;
      }
      try {
        got = pick.client->quorum(requester, remain, attempt_connect_ms);
        LOG_INFO("got lighthouse quorum id=" << got->quorum_id());
        break;
      } catch (const TimeoutError& e) {
        err = e.what();
        err_code = ErrorResponse::DEADLINE_EXCEEDED;
        LOG_ERROR("lighthouse quorum failed: " << err);
        rotate_if_current(pick);
        if (multi && !shutting_down_) {
          // A bounded per-attempt CONNECT timeout is not the client's
          // deadline: keep walking; the loop-top check surfaces the real
          // DEADLINE_EXCEEDED (preserving the reference's TimeoutError
          // mapping, src/lib.rs:321-333) once remain runs out.
          continue;
        }
        break;
      } catch (const RpcError& e) {
        err = e.what();
        err_code = e.code;
        LOG_ERROR("lighthouse quorum failed: " << err);
        rotate_if_current(pick);
        if (e.code == ErrorResponse::UNAVAILABLE && multi &&
            !shutting_down_) {
          // A standby's rejection: walk to the next endpoint (brief
          // pause — a takeover may still be in flight).
          struct timespec ts = {0, 100 * 1000000};
          nanosleep(&ts, nullptr);
          continue;
        }
        break;  // real protocol errors surface to the ranks
      } catch (const std::exception& e) {
        err = e.what();
        err_code = ErrorResponse::UNAVAILABLE;
        LOG_ERROR("lighthouse quorum failed: " << err);
        rotate_if_current(pick);
        if (multi && !shutting_down_) {
          // Transient transport failure (a root killed mid-poll resets
          // the connection; the next connect is refused until the
          // standby takes over): keep walking the failover set inside
          // the client's own deadline — the whole point of the endpoint
          // list is that this never surfaces as a step error. A
          // SINGLE-endpoint manager keeps the classic fast-fail
          // (UNAVAILABLE to the ranks after one attempt).
          struct timespec ts = {0, 200 * 1000000};
          nanosleep(&ts, nullptr);
          continue;
        }
        break;
      }
    }
    lock.lock();
    if (quorum_gen_ == gen) {
      if (got.has_value()) {
        latest_quorum_ = std::move(*got);
        quorum_error_.clear();
      } else {
        quorum_error_ = err;
        quorum_error_code_ = err_code;
      }
      quorum_gen_ += 1;
      quorum_cv_.notify_all();
    } else {
      // A sibling forward already advanced the generation: its (newer)
      // result serves every waiter, including this connection via the
      // wait loop below. Installing ours would roll the state back.
      LOG_WARN("dropping superseded lighthouse quorum result (generation "
               << gen << " -> " << quorum_gen_ << ")");
    }
  }

  while (quorum_gen_ == gen && !shutting_down_) {
    if (deadline < 0) {
      quorum_cv_.wait(lock);
    } else {
      int64_t remain = deadline - now_ms();
      if (remain <= 0) {
        lock.unlock();
        send_error(sock, ErrorResponse::DEADLINE_EXCEEDED, "quorum timed out");
        return;
      }
      quorum_cv_.wait_for(lock, std::chrono::milliseconds(remain));
    }
  }
  if (shutting_down_) {
    lock.unlock();
    send_error(sock, ErrorResponse::CANCELLED, "manager shutting down");
    return;
  }
  if (!quorum_error_.empty()) {
    std::string err = quorum_error_;
    ErrorResponse::Code code = quorum_error_code_;
    lock.unlock();
    send_error(sock, code, err);
    return;
  }
  Quorum quorum = latest_quorum_;
  lock.unlock();

  LOG_INFO("returning quorum for rank " << req.rank());
  try {
    torchft_tpu::ManagerQuorumResponse resp =
        compute_quorum_results(replica_id_, req.rank(), quorum);
    send_msg(sock, MsgType::kManagerQuorumResp, resp);
  } catch (const std::exception& e) {
    send_error(sock, ErrorResponse::NOT_FOUND, e.what());
  }
}

void ManagerServer::handle_should_commit(Socket& sock, const std::string& payload) {
  torchft_tpu::ShouldCommitRequest req;
  if (!req.ParseFromString(payload)) {
    send_error(sock, ErrorResponse::INVALID_ARGUMENT, "bad should_commit request");
    return;
  }
  LOG_INFO("should_commit request from " << req.rank()
                                         << " should_commit=" << req.should_commit());
  int64_t deadline = req.timeout_ms() <= 0 ? -1 : now_ms() + req.timeout_ms();

  UniqueMutexLock lock(mu_);
  if (!req.should_commit()) should_commit_failures_.insert(req.rank());
  should_commit_count_.insert(req.rank());
  int64_t gen = commit_gen_;

  if (should_commit_count_.size() >= world_size_) {
    bool decision = should_commit_failures_.empty();
    LOG_INFO("should_commit completed should_commit=" << decision);
    latest_decision_ = decision;
    should_commit_count_.clear();
    should_commit_failures_.clear();
    commit_gen_ += 1;
    commit_cv_.notify_all();
  }

  while (commit_gen_ == gen && !shutting_down_) {
    if (deadline < 0) {
      commit_cv_.wait(lock);
    } else {
      int64_t remain = deadline - now_ms();
      if (remain <= 0) {
        lock.unlock();
        send_error(sock, ErrorResponse::DEADLINE_EXCEEDED, "should_commit timed out");
        return;
      }
      commit_cv_.wait_for(lock, std::chrono::milliseconds(remain));
    }
  }
  if (shutting_down_) {
    lock.unlock();
    send_error(sock, ErrorResponse::CANCELLED, "manager shutting down");
    return;
  }
  bool decision = latest_decision_;
  lock.unlock();

  torchft_tpu::ShouldCommitResponse resp;
  resp.set_should_commit(decision);
  send_msg(sock, MsgType::kShouldCommitResp, resp);
}

// ---- ManagerClient ----

ManagerClient::ManagerClient(const std::string& addr, int64_t connect_timeout_ms)
    : pool_(addr, connect_timeout_ms) {}

// One request/response on a pooled connection. A SocketError before the
// request was sent triggers one reconnect+resend (these RPCs are idempotent:
// quorum/should_commit register the rank in a set). A desynchronized
// connection — client-side timeout with the response still in flight, or a
// mid-response socket error — is dropped instead of returned to the pool.
template <typename Req, typename Resp>
Resp ManagerClient::roundtrip(uint8_t req_type, const Req& req, uint8_t resp_type,
                              int64_t timeout_ms) {
  int64_t deadline = timeout_ms < 0 ? -1 : now_ms() + timeout_ms;
  Socket sock = pool_.acquire();
  try {
    try {
      send_msg(sock, static_cast<MsgType>(req_type), req, deadline);
    } catch (const SocketError&) {
      // Pooled connection had gone stale; dial a fresh one.
      sock = connect_with_retry(pool_.addr(), pool_.connect_timeout_ms());
      send_msg(sock, static_cast<MsgType>(req_type), req, deadline);
    }
    Resp resp = recv_expect<Resp>(sock, static_cast<MsgType>(resp_type), deadline);
    pool_.release(std::move(sock));
    return resp;
  } catch (const RpcError&) {
    // Error frame fully consumed: the connection is still in sync.
    pool_.release(std::move(sock));
    throw;
  }
  // TimeoutError / SocketError: sock destructs here, dropping the connection.
}

torchft_tpu::ManagerQuorumResponse ManagerClient::quorum(
    int64_t rank, int64_t step, const std::string& checkpoint_metadata,
    bool shrink_only, bool force_reconfigure, int64_t timeout_ms) {
  torchft_tpu::ManagerQuorumRequest req;
  req.set_rank(rank);
  req.set_step(step);
  req.set_checkpoint_metadata(checkpoint_metadata);
  req.set_shrink_only(shrink_only);
  req.set_force_reconfigure(force_reconfigure);
  req.set_timeout_ms(timeout_ms);
  return roundtrip<torchft_tpu::ManagerQuorumRequest,
                   torchft_tpu::ManagerQuorumResponse>(
      static_cast<uint8_t>(MsgType::kManagerQuorumReq), req,
      static_cast<uint8_t>(MsgType::kManagerQuorumResp), timeout_ms);
}

std::string ManagerClient::checkpoint_metadata(int64_t rank, int64_t timeout_ms) {
  torchft_tpu::CheckpointMetadataRequest req;
  req.set_rank(rank);
  req.set_timeout_ms(timeout_ms);
  return roundtrip<torchft_tpu::CheckpointMetadataRequest,
                   torchft_tpu::CheckpointMetadataResponse>(
             static_cast<uint8_t>(MsgType::kCheckpointMetadataReq), req,
             static_cast<uint8_t>(MsgType::kCheckpointMetadataResp), timeout_ms)
      .checkpoint_metadata();
}

bool ManagerClient::should_commit(int64_t rank, int64_t step, bool should_commit,
                                  int64_t timeout_ms) {
  torchft_tpu::ShouldCommitRequest req;
  req.set_rank(rank);
  req.set_step(step);
  req.set_should_commit(should_commit);
  req.set_timeout_ms(timeout_ms);
  return roundtrip<torchft_tpu::ShouldCommitRequest,
                   torchft_tpu::ShouldCommitResponse>(
             static_cast<uint8_t>(MsgType::kShouldCommitReq), req,
             static_cast<uint8_t>(MsgType::kShouldCommitResp), timeout_ms)
      .should_commit();
}

void ManagerClient::kill(const std::string& msg) {
  torchft_tpu::KillRequest req;
  req.set_msg(msg);
  try {
    // Dedicated connection: the peer _exit(1)s without replying, so don't
    // disturb the pool.
    Socket sock = connect_with_retry(pool_.addr(), pool_.connect_timeout_ms());
    int64_t deadline = now_ms() + pool_.connect_timeout_ms();
    send_msg(sock, MsgType::kKillReq, req, deadline);
    recv_expect<torchft_tpu::KillResponse>(sock, MsgType::kKillResp,
                                           now_ms() + 1000);
  } catch (const std::exception&) {
    // expected: connection drops as the process dies
  }
}

} // namespace tft
