"""LocalSGD / DiLoCo tests.

Unit tests against an autospec'd Manager (reference local_sgd_test.py:41-146)
plus thread-per-replica integration with fault injection and the
algorithm-specific oracles (reference local_sgd_integ_test.py:207-316).
"""

import threading
from concurrent.futures import ThreadPoolExecutor
from datetime import timedelta
from typing import Any, Dict
from unittest.mock import create_autospec

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torchft_tpu import (
    FTTrainState,
    HostCollectives,
    Lighthouse,
    Manager,
    Store,
)
from torchft_tpu.collectives import ReduceOp, _completed
from torchft_tpu.local_sgd import AsyncDiLoCo, DiLoCo, LocalSGD
from torchft_tpu.manager import Manager as RealManager


def _state(value: float = 1.0) -> FTTrainState:
    return FTTrainState(
        {"w": jnp.full((4,), value, jnp.float32)}, optax.sgd(0.1)
    )


def _mock_manager(commit: bool = True):
    manager = create_autospec(RealManager, instance=True)
    manager.allreduce.side_effect = (
        lambda tree, op=None, wire=None: _completed(tree)
    )
    manager.should_commit.return_value = commit
    manager._use_async_quorum = False
    return manager


class TestLocalSGDUnit:
    def test_syncs_every_n_steps(self):
        manager = _mock_manager()
        local = LocalSGD(manager, _state(), sync_every=3)
        grads = {"w": jnp.ones((4,))}
        for i in range(5):
            local.step(grads)
        assert manager.start_quorum.call_count == 1  # one sync at step 3
        local.step(grads)
        assert manager.start_quorum.call_count == 2

    def test_step_applied_counts_and_syncs(self):
        # The fused-train-step integration: the caller applies the inner
        # update itself (models.make_train_step); step_applied only does
        # window accounting — params must NOT be touched by it.
        manager = _mock_manager()
        st = _state(2.0)
        local = LocalSGD(manager, st, sync_every=2)
        before = np.asarray(st.params["w"]).copy()
        local.step_applied()
        assert manager.start_quorum.call_count == 0
        assert np.array_equal(np.asarray(st.params["w"]), before)
        local.step_applied()
        assert manager.start_quorum.call_count == 1  # boundary sync

    def test_make_train_step_matches_split_programs(self):
        # One fused program == grad then apply semantically; XLA fuses
        # differently across the program boundary, so float accumulation
        # order (and thus low-order bits) legitimately differs. SGD keeps
        # the update LINEAR in the gradients so that noise stays at float
        # scale (adam's sign normalization would amplify near-zero-grad
        # noise to +-lr).
        from torchft_tpu.models import (
            init_params,
            loss_fn,
            make_train_step,
            tiny_config,
        )

        cfg = tiny_config()
        tx = optax.sgd(0.1)
        params = init_params(cfg, jax.random.PRNGKey(0))
        opt_state = tx.init(params)
        batch = jnp.zeros((2, 16), jnp.int32)

        fused = make_train_step(cfg, tx)
        p1, o1, loss1 = fused(
            jax.tree_util.tree_map(jnp.copy, params),
            jax.tree_util.tree_map(jnp.copy, opt_state),
            batch,
        )

        loss2, grads = jax.value_and_grad(lambda p: loss_fn(cfg, p, batch))(
            params
        )
        updates, o2 = tx.update(grads, opt_state, params)
        p2 = optax.apply_updates(params, updates)

        # Tolerances at bf16 scale: the model's activations (and thus the
        # grads) are bfloat16, whose rounding differs across fusion
        # orders; the test still catches wiring bugs (wrong optimizer,
        # missing apply, sign errors), which produce O(update) errors.
        assert float(loss1) == pytest.approx(float(loss2), rel=1e-2)
        for a, b in zip(
            jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-2, atol=1e-3
            )

    def test_commit_saves_backup(self):
        manager = _mock_manager(commit=True)
        st = _state(1.0)
        local = LocalSGD(manager, st, sync_every=1)
        local.step({"w": jnp.ones((4,))})  # sgd(0.1): w = 1 - 0.1
        np.testing.assert_allclose(np.asarray(st.params["w"]), 0.9)
        np.testing.assert_allclose(local._backup_params["w"], 0.9)

    def test_abort_restores_backup(self):
        manager = _mock_manager(commit=False)
        st = _state(1.0)
        local = LocalSGD(manager, st, sync_every=2)
        local.step({"w": jnp.ones((4,))})
        local.step({"w": jnp.ones((4,))})
        # Window discarded: params back to the last synced value.
        np.testing.assert_allclose(np.asarray(st.params["w"]), 1.0)
        assert local._local_step == 0

    def test_state_dict_roundtrip(self):
        manager = _mock_manager()
        st = _state(2.0)
        local = LocalSGD(manager, st, sync_every=4)
        local.step({"w": jnp.ones((4,))})
        sd = local.state_dict()
        st2 = _state(0.0)
        local2 = LocalSGD(_mock_manager(), st2, sync_every=4)
        local2.load_state_dict(sd)
        np.testing.assert_allclose(
            np.asarray(st2.params["w"]), np.asarray(st.params["w"])
        )
        assert local2._local_step == 1


class TestDiLoCoUnit:
    def test_requires_sync_quorum(self):
        manager = _mock_manager()
        manager._use_async_quorum = True
        with pytest.raises(ValueError):
            DiLoCo(manager, _state(), optax.sgd(0.5), sync_every=2)

    def test_outer_step_moves_toward_inner(self):
        manager = _mock_manager(commit=True)
        st = _state(1.0)
        diloco = DiLoCo(manager, st, optax.sgd(1.0), sync_every=2)
        for _ in range(2):
            diloco.step({"w": jnp.ones((4,))})
        # inner: w = 1 - 0.1 - 0.1 = 0.8; pseudo = 1.0 - 0.8 = 0.2;
        # outer sgd(lr=1): w = 1.0 - 1.0 * 0.2 = 0.8 — toward the inner
        # result, reproducing it exactly at lr=1 (paper sign convention).
        np.testing.assert_allclose(
            np.asarray(st.params["w"]), 0.8, rtol=1e-6
        )
        np.testing.assert_allclose(diloco._backup_params["w"], 0.8, rtol=1e-6)

    def test_abort_restores_without_outer_step(self):
        manager = _mock_manager(commit=False)
        st = _state(1.0)
        diloco = DiLoCo(manager, st, optax.sgd(0.7), sync_every=1)
        diloco.step({"w": jnp.ones((4,))})
        np.testing.assert_allclose(np.asarray(st.params["w"]), 1.0)


class TestAsyncDiLoCoUnit:
    def test_lr1_single_group_degenerates_to_local(self):
        # Invariant: one group + outer SGD(lr=1) makes the delayed outer
        # update G' = B − Δ, so the reconciliation correction vanishes and
        # AsyncDiLoCo must track pure local SGD exactly.
        manager = _mock_manager(commit=True)
        st = _state(1.0)
        ad = AsyncDiLoCo(manager, st, optax.sgd(1.0), sync_every=2)
        ref = _state(1.0)
        grads = {"w": jnp.ones((4,))}
        for _ in range(6):
            ad.step(grads)
            ref.apply_gradients(grads)
        ad.flush()
        np.testing.assert_allclose(
            np.asarray(st.params["w"]), np.asarray(ref.params["w"]), rtol=1e-6
        )

    def test_serial_mode_matches_sync_diloco(self):
        # overlap=False completes the sync AT the boundary; the delayed
        # reconciliation must degenerate to exact synchronous DiLoCo.
        grads = {"w": jnp.ones((4,))}

        serial_state = _state(1.0)
        serial = AsyncDiLoCo(
            _mock_manager(commit=True), serial_state, optax.sgd(0.5),
            sync_every=2, overlap=False,
        )
        ref_state = _state(1.0)
        ref = DiLoCo(
            _mock_manager(commit=True), ref_state, optax.sgd(0.5),
            sync_every=2,
        )
        for _ in range(4):
            serial.step(grads)
            ref.step(grads)
        assert serial._pending is None  # nothing left in flight
        np.testing.assert_allclose(
            np.asarray(serial_state.params["w"]),
            np.asarray(ref_state.params["w"]),
            rtol=1e-6,
        )

    def test_outer_update_applied_one_window_late(self):
        manager = _mock_manager(commit=True)
        st = _state(1.0)
        ad = AsyncDiLoCo(manager, st, optax.sgd(1.0), sync_every=2)
        grads = {"w": jnp.ones((4,))}
        ad.step(grads)
        ad.step(grads)  # boundary k=0: launch, nothing applied yet
        assert manager.allreduce.call_count == 1
        assert manager.should_commit.call_count == 0
        np.testing.assert_allclose(ad._backup_params["w"], 1.0)  # B unchanged
        ad.step(grads)
        ad.step(grads)  # boundary k=1: window 0's sync completes first
        assert manager.should_commit.call_count == 1
        # lr=1 outer: G' = 1 − 0.2 = 0.8 becomes the new global backup.
        np.testing.assert_allclose(ad._backup_params["w"], 0.8, rtol=1e-6)

    def test_abort_rolls_back_only_inflight_window(self):
        manager = _mock_manager(commit=False)
        st = _state(1.0)
        ad = AsyncDiLoCo(manager, st, optax.sgd(1.0), sync_every=2)
        grads = {"w": jnp.ones((4,))}
        for _ in range(4):
            ad.step(grads)  # window 0 launched at step 2, aborted at step 4
        # At the step-4 boundary window 0 (Δ=0.2) is rolled back; window 1's
        # local progress (2 × 0.1) survives on top of B=1.0; then window 1's
        # sync launches (result still pending).
        ad.flush()  # window 1 also aborts: params return to B = 1.0
        np.testing.assert_allclose(np.asarray(st.params["w"]), 1.0, rtol=1e-6)
        np.testing.assert_allclose(ad._backup_params["w"], 1.0)

    def test_bf16_compression_ships_bf16_and_tracks_local(self):
        import jax

        manager = _mock_manager(commit=True)
        seen_dtypes = []

        def capture(tree, op=None):
            seen_dtypes.extend(
                str(l.dtype) for l in jax.tree_util.tree_leaves(tree)
            )
            from torchft_tpu.collectives import _completed

            return _completed(tree)

        manager.allreduce.side_effect = capture
        st = _state(1.0)
        ad = AsyncDiLoCo(
            manager, st, optax.sgd(1.0), sync_every=2, compress="bf16"
        )
        grads = {"w": jnp.ones((4,))}
        for _ in range(4):
            ad.step(grads)
        ad.flush()
        assert seen_dtypes and all(d == "bfloat16" for d in seen_dtypes)
        # lr=1 single group still tracks local training, within bf16 error.
        np.testing.assert_allclose(
            np.asarray(st.params["w"]), 0.6, rtol=2e-2
        )
        assert st.params["w"].dtype == jnp.float32  # master stays f32

    def test_state_dict_flushes_pending(self):
        manager = _mock_manager(commit=True)
        st = _state(1.0)
        ad = AsyncDiLoCo(manager, st, optax.sgd(1.0), sync_every=1)
        ad.step({"w": jnp.ones((4,))})
        sd = ad.state_dict()  # must not checkpoint with a window in flight
        assert ad._pending is None
        np.testing.assert_allclose(sd["backup_params"]["w"], 0.9, rtol=1e-6)


# -- integration: real control plane, threads as replica groups --


class InjectedFailure(Exception):
    pass


def _run_local_sgd_replicas(
    algo: str,
    num_replicas: int,
    num_syncs: int,
    sync_every: int,
    fail_at: Dict[int, int],
):
    """Each replica runs inner steps + periodic sync; fail_at maps
    replica_id -> manager step at which to die once."""
    lighthouse = Lighthouse(
        bind="[::]:0", min_replicas=1, join_timeout_ms=200,
        quorum_tick_ms=50, heartbeat_timeout_ms=1000,
    )
    remaining_failures = dict(fail_at)
    lock = threading.Lock()

    def run_replica(rid: int):
        for attempt in range(3):
            try:
                return _train(rid)
            except InjectedFailure:
                continue
        raise RuntimeError(f"replica {rid} exhausted attempts")

    def _train(rid: int):
        store = Store()
        col = HostCollectives(timeout=timedelta(seconds=10))
        st = FTTrainState(
            {"w": jnp.full((8,), 1.0, jnp.float32)}, optax.sgd(0.05)
        )
        holder: Dict[str, Any] = {}
        manager = Manager(
            collectives=col,
            load_state_dict=lambda sd: holder["algo"].load_state_dict(sd),
            state_dict=lambda: holder["algo"].state_dict(),
            min_replica_size=1,
            use_async_quorum=(algo == "local_sgd"),
            timeout=timedelta(seconds=10),
            quorum_timeout=timedelta(seconds=10),
            connect_timeout=timedelta(seconds=10),
            rank=0,
            world_size=1,
            store_addr=store.address(),
            lighthouse_addr=lighthouse.address(),
            replica_id=f"{algo}_{rid}",
        )
        if algo == "local_sgd":
            holder["algo"] = LocalSGD(manager, st, sync_every)
        else:
            holder["algo"] = DiLoCo(manager, st, optax.sgd(0.7), sync_every)
        algo_obj = holder["algo"]
        try:
            while manager.current_step() < num_syncs:
                with lock:
                    if remaining_failures.get(rid) == manager.current_step():
                        del remaining_failures[rid]
                        raise InjectedFailure(f"{rid}")
                step = manager.current_step()
                grads = {
                    "w": jnp.full((8,), 0.1 * (step + 1), jnp.float32)
                }
                algo_obj.step(grads)
            return {
                "params": np.asarray(st.params["w"]),
                "backup": np.asarray(algo_obj._backup_params["w"]),
            }
        finally:
            manager.shutdown()
            col.shutdown()
            store.shutdown()

    try:
        with ThreadPoolExecutor(max_workers=num_replicas) as ex:
            futs = [ex.submit(run_replica, i) for i in range(num_replicas)]
            return [f.result(timeout=120) for f in futs]
    finally:
        lighthouse.shutdown()


class TestLocalSGDInteg:
    def test_local_sgd_recovery(self):
        results = _run_local_sgd_replicas(
            "local_sgd", num_replicas=2, num_syncs=4, sync_every=2,
            fail_at={1: 1},
        )
        # Model-only oracle (reference local_sgd_integ_test.py:207-214).
        np.testing.assert_array_equal(results[0]["params"], results[1]["params"])

    def test_diloco_recovery(self):
        results = _run_local_sgd_replicas(
            "diloco", num_replicas=2, num_syncs=4, sync_every=2,
            fail_at={1: 1},
        )
        np.testing.assert_array_equal(results[0]["params"], results[1]["params"])
        np.testing.assert_array_equal(results[0]["backup"], results[1]["backup"])


class TestInt8Compression:
    def _manager(self, commit=True, participants=1):
        manager = _mock_manager(commit=commit)
        manager.allgather.side_effect = lambda tree: _completed([tree])
        manager.num_participants.return_value = participants
        return manager

    def test_int8_ships_quantized_payload_via_allgather(self):
        # compress="int8": the DEVICE link carries int8 bytes — the wire
        # payload is {q: int8 leaves, scale: f32} over a managed
        # allgather, dequantize-averaged member-wise on finish.
        import jax

        manager = self._manager()
        seen = []
        manager.allgather.side_effect = lambda tree: (
            seen.append(tree), _completed([tree])
        )[1]
        st = _state(1.0)
        ad = AsyncDiLoCo(
            manager, st, optax.sgd(1.0), sync_every=2, compress="int8"
        )
        grads = {"w": jnp.ones((4,))}
        for _ in range(4):
            ad.step(grads)
        ad.flush()
        assert seen and all(
            str(l.dtype) == "int8"
            for e in seen
            for l in jax.tree_util.tree_leaves(e["q"])
        )
        assert all("scale" in e for e in seen)
        np.testing.assert_allclose(
            np.asarray(st.params["w"]), 0.6, atol=0.01
        )

    def test_ships_quantized_grid_over_q8_wire(self):
        import jax

        manager = self._manager()
        seen = []

        def capture(tree, op=None, wire=None):
            seen.append((tree, op, wire))
            return _completed(tree)

        manager.allreduce.side_effect = capture
        st = _state(1.0)
        ad = AsyncDiLoCo(
            manager, st, optax.sgd(1.0), sync_every=2, compress="q8"
        )
        grads = {"w": jnp.ones((4,))}
        for _ in range(4):
            ad.step(grads)
        ad.flush()
        assert seen
        for tree, op, wire in seen:
            # rides the ring's quantized wire with the participant average
            assert wire == "q8" and op == ReduceOp.AVG
            for l in jax.tree_util.tree_leaves(tree):
                # the shipped delta is the DEQUANTIZED local value: every
                # element sits on its leaf's int8 grid (d = k * scale for
                # integer k in [-127, 127])
                arr = np.asarray(l, np.float64)
                scale = np.abs(arr).max() / 127 if np.abs(arr).max() else 1.0
                k = arr / scale
                np.testing.assert_allclose(k, np.round(k), atol=1e-3)
        # lr=1 single group tracks local training within one quantization
        # step of the largest delta (scale = max|d|/127)
        np.testing.assert_allclose(
            np.asarray(st.params["w"]), 0.6, atol=0.01
        )
        assert st.params["w"].dtype == jnp.float32

    def test_error_feedback_prevents_drift(self):
        # Many windows with a delta that does NOT quantize exactly: with
        # EF the accumulated shipped sum stays within ONE quantization
        # step of the true sum; without EF the per-window bias would
        # accumulate linearly.
        manager = self._manager()
        st = _state(1.0)
        ad = AsyncDiLoCo(
            manager, st, optax.sgd(1.0), sync_every=1, compress="int8"
        )
        # gradient chosen so delta/scale is irrational-ish per window
        grads = {"w": jnp.asarray([0.1, 0.0333, 0.00777, 0.0001])}
        windows = 20
        for _ in range(windows):
            ad.step(grads)
        ad.flush()
        # inner sgd lr=0.1 -> per-window delta = 0.1 * grad
        expect = 1.0 - windows * 0.1 * np.asarray(grads["w"])
        # one quantization step = max|d|/127 = 0.01/127 per window; EF
        # keeps TOTAL error near one step, far below windows * step
        step_q = 0.01 / 127
        err = np.max(np.abs(np.asarray(st.params["w"]) - expect))
        assert err < 3 * step_q, (err, step_q)

    def test_abort_restores_residual_and_rolls_back(self):
        manager = self._manager(commit=False)
        st = _state(1.0)
        ad = AsyncDiLoCo(
            manager, st, optax.sgd(1.0), sync_every=1, compress="int8"
        )
        ad.step({"w": jnp.ones((4,))})  # window ships, will abort
        ad.flush()
        # rollback: params return to backup
        np.testing.assert_allclose(
            np.asarray(st.params["w"]), 1.0, atol=1e-6
        )
        # aborted window's EF update discarded
        np.testing.assert_allclose(
            np.asarray(ad._residual["w"]), 0.0, atol=1e-9
        )

    def test_averaged_result_applied_directly(self):
        # The q8 ring returns the PARTICIPANT-AVERAGED delta tree directly
        # (the zero-contribution/divisor discipline lives in
        # Manager.allreduce, covered by the manager tests; the native
        # quantized ring itself by test_collectives). Here: whatever
        # averaged tree the wire resolves to is what the outer update
        # consumes — simulate a 2-member average halving our delta.
        manager = self._manager(participants=2)

        def halved(tree, op=None, wire=None):
            import jax

            return _completed(
                jax.tree_util.tree_map(lambda l: l / 2, tree)
            )

        manager.allreduce.side_effect = halved
        st = _state(1.0)
        ad = AsyncDiLoCo(
            manager, st, optax.sgd(1.0), sync_every=1, compress="q8"
        )
        ad.step({"w": jnp.ones((4,))})  # inner lr 0.1 -> own delta 0.1
        ad.flush()
        # averaged delta 0.05 applied by the lr-1 outer sgd
        np.testing.assert_allclose(
            np.asarray(st.params["w"]), 0.95, atol=0.001
        )
