"""XLACollectives: jit-compiled cross-group collectives over a multi-process
global mesh (the DCN data-plane option; see torchft_tpu/xla_collectives.py
and DCN.md).

Each test runs 2 worker subprocesses (one per "replica group") because
``jax.distributed.initialize`` binds the whole process to the cohort — the
pytest process itself must stay unpolluted. Workers rendezvous through a
Store owned by the test, exactly as the Manager would drive it.
"""

import os
import subprocess
import sys
import textwrap
from datetime import timedelta

import numpy as np
import pytest

from conftest import CPU_MULTIPROCESS_SKIP, HAS_CPU_MULTIPROCESS

if not HAS_CPU_MULTIPROCESS:
    # every test here runs cross-process CPU computations in worker
    # subprocesses; without a CPU collectives backend they all raise
    # "Multiprocess computations aren't implemented on the CPU backend"
    pytest.skip(CPU_MULTIPROCESS_SKIP, allow_module_level=True)

from torchft_tpu import Store

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER_PRELUDE = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("JAX_CPU_COLLECTIVES_IMPLEMENTATION", "gloo")
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp
    from datetime import timedelta
    from torchft_tpu import XLACollectives
    from torchft_tpu.collectives import ReduceOp

    rank = int(sys.argv[1])
    store_addr = sys.argv[2]
    xc = XLACollectives(timeout=timedelta(seconds=60),
                        connect_timeout=timedelta(seconds=60))
    """
).format(repo=REPO)


def _run_workers(
    body: str, nprocs: int = 2, timeout: float = 180.0, devices_per_proc: int = 1
):
    """Runs the worker script in nprocs subprocesses; returns stdouts."""
    store = Store()
    script = _WORKER_PRELUDE + textwrap.dedent(body)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    if devices_per_proc > 1:
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={devices_per_proc}"
        )
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", script, str(r), store.address()],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for r in range(nprocs)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append((p.returncode, out))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        store.shutdown()
    for rc, out in outs:
        assert rc == 0, f"worker failed:\n{out}"
    return [out for _, out in outs]


class TestXLACollectives:
    def test_allreduce_sum_avg_and_tree(self):
        outs = _run_workers(
            """
            xc.configure(store_addr + "/q0", rank, 2)
            tree = {"a": jnp.full((3,), float(rank + 1)),
                    "b": jnp.arange(4, dtype=jnp.float32) * (rank + 1)}
            s = xc.allreduce(tree, ReduceOp.SUM).wait()
            assert np.allclose(np.asarray(s["a"]), 3.0), s
            assert np.allclose(np.asarray(s["b"]), np.arange(4) * 3.0), s
            a = xc.allreduce(tree, ReduceOp.AVG).wait()
            assert np.allclose(np.asarray(a["a"]), 1.5), a
            assert a["a"].dtype == tree["a"].dtype
            # Integer AVG floor-divides, same dtype (host-ring contract).
            iv = xc.allreduce(jnp.full((2,), 3 + rank, jnp.int32),
                              ReduceOp.AVG).wait()
            assert iv.dtype == jnp.int32 and int(iv[0]) == 3, iv
            # Results are local arrays a per-group jit can consume.
            y = jax.jit(lambda t: t["a"] * 2)(s)
            assert np.allclose(np.asarray(y), 6.0)
            print("OK", xc.size(), xc.rank())
            xc.shutdown()
            """
        )
        for r, out in enumerate(outs):
            assert f"OK 2 {r}" in out

    def test_broadcast_and_allgather(self):
        outs = _run_workers(
            """
            xc.configure(store_addr + "/q0", rank, 2)
            tree = jnp.full((2,), float(rank * 10 + 1))
            b = xc.broadcast(tree, root=1).wait()
            assert np.allclose(np.asarray(b), 11.0), b
            g = xc.allgather(tree).wait()
            assert len(g) == 2
            assert np.allclose(np.asarray(g[0]), 1.0)
            assert np.allclose(np.asarray(g[1]), 11.0)
            xc.barrier().wait()
            print("OK")
            xc.shutdown()
            """
        )
        for out in outs:
            assert "OK" in out

    def test_multi_device_processes(self):
        # The target deployment: one process per TPU slice with SEVERAL
        # local chips. The mesh is (replica, local); collectives must agree
        # and results must be consumable by a local jit.
        outs = _run_workers(
            """
            xc.configure(store_addr + "/q0", rank, 2)
            assert jax.local_device_count() == 2
            mesh = xc.global_mesh()
            assert dict(zip(mesh.axis_names, mesh.devices.shape)) == (
                {"replica": 2, "local": 2}
            )
            tree = {"g": jnp.full((5,), float(rank + 1))}
            s = xc.allreduce(tree, ReduceOp.AVG).wait()
            assert np.allclose(np.asarray(s["g"]), 1.5), s
            g = xc.allgather(jnp.full((2,), float(rank))).wait()
            assert np.allclose(np.asarray(g[1]), 1.0)
            print("OK")
            xc.shutdown()
            """,
            devices_per_proc=2,
        )
        for out in outs:
            assert "OK" in out

    def test_configure_after_jax_use(self):
        # Manager drop-in reality: the user builds params on device BEFORE
        # the first quorum configures the collectives. The backend must
        # clear and re-initialize instead of raising.
        outs = _run_workers(
            """
            pre = jax.jit(lambda: jnp.ones((3,)) * 2)()  # backend init'd
            jax.block_until_ready(pre)
            xc.configure(store_addr + "/q0", rank, 2)
            s = xc.allreduce(jnp.full((3,), float(rank + 1))).wait()
            assert np.allclose(np.asarray(s), 3.0), s
            print("OK")
            xc.shutdown()
            """
        )
        for out in outs:
            assert "OK" in out

    def test_reconfigure_state_survival(self):
        # The automated form of the snapshot-to-host discipline the module
        # docstring prescribes (xla_collectives.py:19-31): an FTTrainState
        # registered via register_state() is host-round-tripped across the
        # distributed-runtime teardown that reconfigure performs, and
        # training continues from exactly the pre-reconfigure state.
        outs = _run_workers(
            """
            import optax
            from torchft_tpu import FTTrainState

            state = FTTrainState({"w": jnp.ones((4,)) * 2.0},
                                 optax.sgd(0.1))
            xc.register_state(state)
            xc.configure(store_addr + "/q0", rank, 2)

            def train_step():
                # rank-dependent grads, shared average: both ranks apply
                # the same update to the same initial state
                grads = {"w": state.params["w"] * (0.5 * (rank + 1))}
                avg = xc.allreduce(grads, ReduceOp.AVG).wait()
                state.apply_gradients(avg)

            for _ in range(3):
                train_step()
            before = np.asarray(state.params["w"]).copy()
            opt_before = jax.tree_util.tree_map(
                np.asarray, state.opt_state
            )

            xc.configure(store_addr + "/q1", rank, 2)  # membership change

            after = np.asarray(state.params["w"])
            assert np.array_equal(before, after), (before, after)
            # opt_state survived too (momentum etc. restored bitwise)
            for a, b in zip(
                jax.tree_util.tree_leaves(opt_before),
                jax.tree_util.tree_leaves(
                    jax.tree_util.tree_map(np.asarray, state.opt_state)
                ),
            ):
                assert np.array_equal(np.asarray(a), np.asarray(b))

            for _ in range(2):
                train_step()  # continues on the new backend
            final = np.asarray(state.params["w"])
            assert not np.array_equal(before, final)
            print("OK", final.tolist())
            xc.shutdown()
            """
        )
        # Both ranks applied identical averaged updates throughout, so
        # their trained states agree.
        finals = [out.splitlines()[-1] for out in outs]
        assert finals[0] == finals[1], finals

    def test_failed_reconfigure_still_restores_state(self):
        # Round-3 advisor (medium): if jax.distributed.initialize fails
        # AFTER teardown_backends() orphaned the registered holders'
        # arrays, the snapshots must survive to the next successful
        # configure — a local snapshot list leaked them and training
        # silently continued on stale-backend arrays. The injected
        # failure is a non-RuntimeError so configure()'s retry-once
        # branch doesn't swallow it.
        outs = _run_workers(
            """
            import optax
            from torchft_tpu import FTTrainState

            state = FTTrainState({"w": jnp.ones((4,)) * 2.0},
                                 optax.sgd(0.1))
            xc.register_state(state)
            xc.configure(store_addr + "/q0", rank, 2)
            for _ in range(2):
                grads = {"w": state.params["w"] * (0.5 * (rank + 1))}
                avg = xc.allreduce(grads, ReduceOp.AVG).wait()
                state.apply_gradients(avg)
            before = np.asarray(state.params["w"]).copy()

            import jax.distributed as jd
            real_init = jd.initialize
            first = {"v": True}
            def flaky(**kw):
                if first["v"]:
                    first["v"] = False
                    raise ValueError("injected coordinator outage")
                return real_init(**kw)
            jd.initialize = flaky
            try:
                xc.configure(store_addr + "/q1", rank, 2)
                raise SystemExit("expected injected failure")
            except ValueError:
                pass
            jd.initialize = real_init

            # next configure succeeds and must restore the pre-teardown
            # state from the carried-over snapshots
            xc.configure(store_addr + "/q2", rank, 2)
            after = np.asarray(state.params["w"])
            assert np.array_equal(before, after), (before, after)
            grads = {"w": state.params["w"] * (0.5 * (rank + 1))}
            avg = xc.allreduce(grads, ReduceOp.AVG).wait()
            state.apply_gradients(avg)
            print("OK", np.asarray(state.params["w"]).tolist())
            xc.shutdown()
            """
        )
        finals = [out.splitlines()[-1] for out in outs]
        assert finals[0] == finals[1], finals

    def test_reconfigure_new_membership(self):
        # Quorum change: same cohort re-rendezvous on a new prefix; the
        # runtime is rebuilt and collectives still agree. Pre-reconfigure
        # arrays are orphaned but — measured on CPU, pinned here — keep
        # their data (the docstring contract: not guaranteed on
        # accelerators, snapshot to host around reconfigure).
        outs = _run_workers(
            """
            xc.configure(store_addr + "/q0", rank, 2)
            stale = xc.allreduce(jnp.ones((2,)), ReduceOp.SUM).wait()
            xc.configure(store_addr + "/q1", rank, 2)
            fresh = xc.allreduce(jnp.full((2,), 2.0), ReduceOp.SUM).wait()
            assert np.allclose(np.asarray(fresh), 4.0), fresh
            assert np.allclose(np.asarray(stale), 2.0), stale
            print("OK")
            xc.shutdown()
            """
        )
        for out in outs:
            assert "OK" in out
