"""Data sharding across replica groups and ranks.

Reference: torchft/data.py — a DistributedSampler sharding by
``global_rank = rank + num_replicas * replica_group`` over
``num_replicas * num_replica_groups`` shards (data.py:46-77). Like the
reference, this is documented-lossy under faults: when a replica group dies
and rejoins, it resumes from its own dataloader position; exactly-once data
visitation is out of scope (reference data.py:33-36).
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np


class DistributedSampler:
    """Yields dataset indices for this (replica_group, rank)'s shard.

    Args:
        dataset_len: total number of examples.
        replica_group: which fault-tolerance replica group this is.
        num_replica_groups: total replica groups.
        rank: rank within the replica group (0 for pure DP).
        num_replicas: ranks per replica group.
        shuffle: reshuffle each epoch (seeded, identical on all shards).
        seed: base RNG seed shared by every shard.
    """

    def __init__(
        self,
        dataset_len: int,
        replica_group: int,
        num_replica_groups: int,
        rank: int = 0,
        num_replicas: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ) -> None:
        self._dataset_len = dataset_len
        # Reference data.py:46-77: one flat shard space over all ranks of
        # all replica groups.
        self.global_rank = rank + num_replicas * replica_group
        self.global_world_size = num_replicas * num_replica_groups
        self._shuffle = shuffle
        self._seed = seed
        self._drop_last = drop_last
        self._epoch = 0
        if drop_last:
            self.num_samples = dataset_len // self.global_world_size
        else:
            self.num_samples = -(-dataset_len // self.global_world_size)

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def __len__(self) -> int:
        return self.num_samples

    def __iter__(self) -> Iterator[int]:
        if self._shuffle:
            rng = np.random.default_rng(self._seed + self._epoch)
            order = rng.permutation(self._dataset_len)
        else:
            order = np.arange(self._dataset_len)
        if not self._drop_last:
            # Pad to a multiple of the world size by wrapping, so every
            # shard has the same length (torch DistributedSampler semantics).
            pad = self.num_samples * self.global_world_size - len(order)
            if pad > 0:
                order = np.concatenate([order, order[:pad]])
        else:
            order = order[: self.num_samples * self.global_world_size]
        yield from order[self.global_rank :: self.global_world_size].tolist()
