"""Weight-distribution serving-plane benchmark (PS_BENCH.json).

Puts numbers on the serving tier's three perf claims (serving.py):

  wire efficiency   per-subscriber bytes are proportional to the WIRE
                    size, not the f32 size — measured from real
                    subscriber fetch counters: q8 <= 0.30x and
                    bf16 <= 0.55x of the f32 bytes for the same tree.
  fan-out scaling   publish cost is amortized once per version: with a
                    two-tier relay chain, the ROOT's payload egress per
                    version is identical at 50 and at 200+ subscribers
                    (bytes move out of the root once per child, never
                    per subscriber), while the p99 publish->install
                    version lag across the whole fleet stays bounded.
  fault recovery    a late/paused subscriber catches up via DELTAS (not
                    a full snapshot), and a publisher SIGKILLed MID-range
                    (drip-throttled bodies guarantee the kill lands
                    inside a transfer) then respawned leaves every
                    downstream install intact: zero torn installs,
                    detections counted.

Topology (all on this host, CPU JAX): one publisher, relay tier 1 (one
relay), relay tier 2 (two relays), subscribers split across tier 2.
Subscribers are real WeightSubscriber sessions driven round-robin by a
small worker pool — "simulated" in the sense that they share threads,
not sockets; every fetch is a real HTTP range read with the full
integrity ladder.

``--dryrun`` is the CI smoke: seconds-scale, asserts at least one
delta-catch-up record and one publisher-kill-mid-range recovery record,
writes no artifact. The full run stamps PS_BENCH.json with
``chaos.bench_fault_stamp`` so a bench-observed anomaly replays via
``scripts/chaos_run.py --config serving_churn``.

Usage::

    python bench_ps.py                  # full sweep -> PS_BENCH.json
    python bench_ps.py --dryrun         # CI smoke, no artifact
    python bench_ps.py --subscribers 400
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from torchft_tpu import chaos  # noqa: E402
from torchft_tpu.serving import (  # noqa: E402
    WeightPublisher,
    WeightRelay,
    WeightSubscriber,
    demo_params,
    tree_digest,
)


def _pct(values: List[float], q: float) -> float:
    if not values:
        return float("nan")
    return float(np.percentile(np.asarray(values, dtype=np.float64), q))


# --------------------------------------------------------------------------
# phase 1: wire efficiency (measured from subscriber fetch counters)
# --------------------------------------------------------------------------


def bench_wire_bytes(leaves: int, elems: int, versions: int) -> Dict[str, Any]:
    """Per-subscriber bytes by wire, measured end to end: one subscriber
    follows ``versions`` publishes (snapshot + deltas) and its
    ``bytes_fetched`` counter IS the per-subscriber cost."""
    out: Dict[str, Any] = {"leaves": leaves, "elems": elems,
                           "versions": versions}
    f32_nbytes = leaves * elems * 4
    measured: Dict[str, int] = {}
    for wire in ("f32", "bf16", "q8"):
        pub = WeightPublisher(wire=wire, snapshot_every=versions + 1)
        try:
            sub = WeightSubscriber(
                pub.server.local_address(), name=f"wire-{wire}"
            )
            t0 = time.monotonic()
            for v in range(versions):
                pub.publish(demo_params(3, leaves, elems, v), step=v)
                assert sub.poll() is True
            wall = time.monotonic() - t0
            assert sub.version() == versions - 1
            assert sub.stats["torn_installs"] == 0
            measured[wire] = sub.stats["bytes_fetched"]
            out[wire] = {
                "bytes_fetched": sub.stats["bytes_fetched"],
                "bytes_per_version": sub.stats["bytes_fetched"] // versions,
                "installs": sub.stats["installs"],
                "wall_s": round(wall, 3),
            }
            sub.close()
        finally:
            pub.shutdown()
    out["f32_nbytes_per_version"] = f32_nbytes
    out["q8_ratio_vs_f32"] = round(measured["q8"] / measured["f32"], 4)
    out["bf16_ratio_vs_f32"] = round(measured["bf16"] / measured["f32"], 4)
    # the tentpole's measured wire targets
    assert out["q8_ratio_vs_f32"] <= 0.30, out
    assert out["bf16_ratio_vs_f32"] <= 0.55, out
    return out


# --------------------------------------------------------------------------
# phase 2: fan-out scaling (root egress flat, p99 lag bounded)
# --------------------------------------------------------------------------


def bench_fanout(
    n_subscribers: int,
    versions: int,
    leaves: int,
    elems: int,
    publish_every_ms: int,
    pool_workers: int = 8,
) -> Dict[str, Any]:
    """``n_subscribers`` real subscriber sessions behind a two-tier relay
    chain, a worker pool driving their polls; measures the publish ->
    install lag distribution fleet-wide and the ROOT's payload egress per
    version."""
    pub = WeightPublisher(wire="q8", snapshot_every=4)
    r1 = WeightRelay(pub.server.local_address(), name="fan-r1",
                     poll_timeout_ms=200).start()
    tier2 = [
        WeightRelay(r1.server.local_address(), name=f"fan-r2{i}",
                    poll_timeout_ms=200).start()
        for i in range(2)
    ]
    subs = [
        WeightSubscriber(
            tier2[i % len(tier2)].server.local_address(),
            name=f"fan-s{i}",
            lease_ttl_ms=30_000,
        )
        for i in range(n_subscribers)
    ]
    publish_mono: Dict[int, float] = {}
    install_lags_ms: List[float] = []
    lag_lock = threading.Lock()
    stop = threading.Event()

    def drive(shard: List[WeightSubscriber]) -> None:
        while not stop.is_set():
            idle = True
            for s in shard:
                before = s.version()
                if s.poll() and not stop.is_set():
                    idle = False
                    now = time.monotonic()
                    after = s.version()
                    with lag_lock:
                        for v in range(before + 1, after + 1):
                            if v in publish_mono:
                                install_lags_ms.append(
                                    (now - publish_mono[v]) * 1000.0
                                )
            if idle:
                stop.wait(0.05)

    shards = [subs[i::pool_workers] for i in range(pool_workers)]
    threads = [
        threading.Thread(target=drive, args=(sh,), daemon=True)
        for sh in shards if sh
    ]
    try:
        t0 = time.monotonic()
        for t in threads:
            t.start()
        root0 = dict(pub.node.counters)
        for v in range(versions):
            with lag_lock:
                publish_mono[v] = time.monotonic()
            pub.publish(demo_params(3, leaves, elems, v), step=v)
            time.sleep(publish_every_ms / 1000.0)
        # drain: every subscriber reaches the last version
        deadline = time.monotonic() + 120.0
        last = versions - 1
        while time.monotonic() < deadline:
            if all(s.version() == last for s in subs):
                break
            time.sleep(0.1)
        assert all(s.version() == last for s in subs), (
            f"fleet never converged to v{last} "
            f"(behind={sum(1 for s in subs if s.version() < last)})"
        )
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
        root1 = dict(pub.node.counters)
        want = pub.node.store.get(last).manifest["digest"]
        sample = subs[:: max(1, n_subscribers // 16)]
        for s in sample:
            assert tree_digest(s.current()[1]) == want
        torn = sum(s.stats["torn_installs"] for s in subs)
        assert torn == 0, f"{torn} torn installs"
        wall = time.monotonic() - t0
        per_sub_bytes = [s.stats["bytes_fetched"] for s in subs]
        return {
            "subscribers": n_subscribers,
            "versions": versions,
            "relay_tiers": 2,
            "pool_workers": pool_workers,
            "wall_s": round(wall, 3),
            "lag_ms": {
                "n": len(install_lags_ms),
                "p50": round(_pct(install_lags_ms, 50), 1),
                "p95": round(_pct(install_lags_ms, 95), 1),
                "p99": round(_pct(install_lags_ms, 99), 1),
                "max": round(max(install_lags_ms), 1)
                if install_lags_ms else float("nan"),
            },
            "root": {
                "ranges_served_per_version": (
                    (root1["ranges_served"] - root0["ranges_served"])
                    / versions
                ),
                "meta_served_per_version": (
                    (root1["meta_served"] - root0["meta_served"]) / versions
                ),
                "payload_egress_bytes": (
                    root1["egress_bytes"] - root0["egress_bytes"]
                ),
            },
            "per_subscriber_bytes": {
                "p50": int(_pct([float(b) for b in per_sub_bytes], 50)),
                "max": max(per_sub_bytes),
            },
            "torn_installs": 0,
        }
    finally:
        stop.set()
        for s in subs:
            try:
                s.close()
            except Exception:  # noqa: BLE001 - teardown best effort
                pass
        for r in tier2:
            r.shutdown()
        r1.shutdown()
        pub.shutdown()


# --------------------------------------------------------------------------
# phase 3: fault-path records (the dryrun's asserted evidence)
# --------------------------------------------------------------------------


def bench_delta_catch_up(versions: int = 8) -> Dict[str, Any]:
    """A subscriber that pauses, misses several publishes, then catches
    up: the catch-up must ride DELTAS (cheap) whenever the chain is
    held, not re-fetch a snapshot."""
    pub = WeightPublisher(wire="q8", snapshot_every=64)
    try:
        sub = WeightSubscriber(pub.server.local_address(), name="cu")
        pub.publish(demo_params(5, 2, 8192, 0), step=0)
        assert sub.poll() is True
        # the pause: publisher moves on without us
        for v in range(1, versions):
            pub.publish(demo_params(5, 2, 8192, v), step=v)
        t0 = time.monotonic()
        assert sub.poll() is True
        catch_up_s = time.monotonic() - t0
        assert sub.version() == versions - 1
        assert sub.stats["catch_up_deltas"] >= versions - 1
        assert sub.stats["snapshot_installs"] == 1  # only the initial one
        assert tree_digest(sub.current()[1]) == (
            pub.node.store.get(versions - 1).manifest["digest"]
        )
        rec = {
            "type": "delta_catch_up",
            "missed_versions": versions - 1,
            "catch_up_deltas": sub.stats["catch_up_deltas"],
            "snapshot_refetches": 0,
            "catch_up_s": round(catch_up_s, 3),
            "bytes_fetched": sub.stats["bytes_fetched"],
            "bit_identity_ok": True,
        }
        sub.close()
        return rec
    finally:
        pub.shutdown()


def bench_kill_mid_range(seed: int = 4242) -> Dict[str, Any]:
    """Publisher SIGKILL mid-range (drip-throttled subprocess), respawn
    on the same port, downstream recovery: the relay's in-flight fetch
    dies as a SHORT body (counted), the subscriber never sees a torn
    tree, and the fleet converges on the respawned history."""
    from torchft_tpu.chaos import PublisherProcess, free_port
    from torchft_tpu.serving import _http_json

    pub = PublisherProcess(
        free_port(), wire="q8", leaves=4, elems=65536, seed=seed,
        publish_every_ms=150, snapshot_every=4, drip_ms=15,
    )
    relay = None
    sub = None
    try:
        pub.wait_serving(min_version=1)
        relay = WeightRelay(pub.address(), name="kill-r",
                            poll_timeout_ms=200).start()
        sub = WeightSubscriber(
            relay.server.local_address(), name="kill-s"
        ).start(poll_ms=100)
        deadline = time.monotonic() + 30.0
        while sub.version() < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert sub.version() >= 1, "subscriber never started installing"
        v_before = sub.version()
        t_kill = time.monotonic()
        pub.kill()
        time.sleep(0.4)  # short bodies land at the relay
        pub.restart()
        pub.wait_serving(min_version=1)
        # recovery: the subscriber converges onto the NEW history
        deadline = time.monotonic() + 60.0
        recovered_v = -1
        while time.monotonic() < deadline:
            v = sub.version()
            listing = _http_json(f"{pub.address()}/ps/versions", 5.0)
            manifests = {
                int(m["version"]): m for m in listing.get("versions", [])
            }
            if v in manifests and tree_digest(sub.current()[1]) == (
                manifests[v]["digest"]
            ):
                recovered_v = v
                break
            time.sleep(0.1)
        recovery_s = time.monotonic() - t_kill
        assert recovered_v >= 0, "subscriber never recovered post-kill"
        assert sub.stats["torn_installs"] == 0
        detections = {
            k: v for k, v in sub.stats.items()
            if k.startswith("detect_") and v
        }
        relay_errors = relay.node.counters["upstream_errors"]
        assert relay_errors > 0 or detections, (
            "kill produced no counted detection anywhere downstream"
        )
        return {
            "type": "kill_mid_range_recovery",
            "drip_ms": 15,
            "version_at_kill": v_before,
            "recovered_version": recovered_v,
            "recovery_s": round(recovery_s, 3),
            "relay_upstream_errors": relay_errors,
            "subscriber_detections": detections,
            "torn_installs": 0,
            "bit_identity_ok": True,
        }
    finally:
        if sub is not None:
            sub.close()
        if relay is not None:
            relay.shutdown()
        pub.stop()


# --------------------------------------------------------------------------
# entry point
# --------------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--dryrun", action="store_true",
                        help="seconds-scale CI smoke; no artifact")
    parser.add_argument("--subscribers", type=int, default=200,
                        help="fleet size for the big fan-out point")
    parser.add_argument("--versions", type=int, default=8)
    parser.add_argument("--leaves", type=int, default=2)
    parser.add_argument("--elems", type=int, default=8192)
    parser.add_argument("--publish-every-ms", type=int, default=400)
    parser.add_argument("--out", default=os.path.join(REPO, "PS_BENCH.json"))
    args = parser.parse_args(argv)

    t0 = time.monotonic()
    records: List[Dict[str, Any]] = []

    wire = bench_wire_bytes(
        leaves=4, elems=4096 if args.dryrun else 65536,
        versions=3 if args.dryrun else 6,
    )
    print(f"[ps] wire bytes: q8={wire['q8_ratio_vs_f32']}x "
          f"bf16={wire['bf16_ratio_vs_f32']}x of f32", flush=True)

    fan_points: List[Dict[str, Any]] = []
    sizes = [24] if args.dryrun else [50, args.subscribers]
    for n in sizes:
        point = bench_fanout(
            n_subscribers=n,
            versions=3 if args.dryrun else args.versions,
            leaves=args.leaves,
            elems=args.elems,
            publish_every_ms=200 if args.dryrun else args.publish_every_ms,
        )
        fan_points.append(point)
        print(
            f"[ps] fanout n={n}: p99 lag {point['lag_ms']['p99']}ms, "
            f"root {point['root']['ranges_served_per_version']} "
            f"ranges/version", flush=True,
        )
    if len(fan_points) == 2:
        # THE fan-out claim: scaling subscribers 4x moves zero extra
        # payload out of the root.
        a, b = fan_points
        assert a["root"]["ranges_served_per_version"] == (
            b["root"]["ranges_served_per_version"]
        ), (a["root"], b["root"])
        assert a["root"]["meta_served_per_version"] == (
            b["root"]["meta_served_per_version"]
        ), (a["root"], b["root"])

    catch_up = bench_delta_catch_up(versions=4 if args.dryrun else 8)
    records.append(catch_up)
    print(f"[ps] delta catch-up: {catch_up['catch_up_deltas']} deltas in "
          f"{catch_up['catch_up_s']}s", flush=True)

    kill = bench_kill_mid_range()
    records.append(kill)
    print(f"[ps] kill mid-range: recovered v{kill['recovered_version']} "
          f"in {kill['recovery_s']}s, "
          f"relay errors={kill['relay_upstream_errors']}", flush=True)

    # the dryrun's contract: both fault-path records present and clean
    assert any(
        r["type"] == "delta_catch_up" and r["catch_up_deltas"] >= 1
        for r in records
    ), "no delta-catch-up record was produced"
    assert any(
        r["type"] == "kill_mid_range_recovery"
        and r["torn_installs"] == 0
        and r["bit_identity_ok"]
        for r in records
    ), "no publisher-kill-mid-range recovery record was produced"

    if args.dryrun:
        print(json.dumps({
            "dryrun": True,
            "q8_ratio_vs_f32": wire["q8_ratio_vs_f32"],
            "bf16_ratio_vs_f32": wire["bf16_ratio_vs_f32"],
            "fanout_points": len(fan_points),
            "delta_catch_up_records": 1,
            "kill_recovery_records": 1,
        }))
        print("ps bench dryrun OK (no artifact written)")
        return 0

    artifact = {
        "phase": "serving",
        "host": {"cpus": os.cpu_count()},
        "wall_s": round(time.monotonic() - t0, 1),
        "config": {
            "leaves": args.leaves,
            "elems": args.elems,
            "publish_every_ms": args.publish_every_ms,
            "relay_tiers": 2,
        },
        "wire_bytes": wire,
        "fanout": fan_points,
        "fault_records": records,
        "fault_plan": chaos.bench_fault_stamp(
            kill_drip_ms=15,
            kill_config="serving_churn",
        ),
    }
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
